"""REST facade + JWT + script-manager tests [SURVEY.md §1 L7, §2.1].

Uses a raw asyncio HTTP client against the real listening socket — the
same surface an external SiteWhere client uses.
"""

import asyncio
import base64
import contextlib
import json

from sitewhere_tpu.config import InstanceSettings
from sitewhere_tpu.kernel.security import TokenManagement
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.services import (
    AssetManagementService,
    BatchOperationsService,
    CommandDeliveryService,
    DeviceManagementService,
    DeviceRegistrationService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
    InstanceManagementService,
    LabelGenerationService,
    OutboundConnectorsService,
    RuleProcessingService,
    ScheduleManagementService,
)

from tests.test_pipeline import wait_until


async def http(port, method, path, *, token=None, body=None, basic=None,
               tenant=None, raw=False):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = json.dumps(body).encode() if body is not None else b""
    headers = [f"{method} {path} HTTP/1.1", "Host: localhost",
               f"Content-Length: {len(payload)}"]
    if token:
        headers.append(f"Authorization: Bearer {token}")
    if basic:
        headers.append("Authorization: Basic "
                       + base64.b64encode(basic.encode()).decode())
    if tenant:
        headers.append(f"X-SiteWhere-Tenant: {tenant}")
    writer.write(("\r\n".join(headers) + "\r\n\r\n").encode() + payload)
    await writer.drain()
    status_line = await reader.readline()
    status = int(status_line.split()[1])
    resp_headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode().partition(":")
        resp_headers[k.strip().lower()] = v.strip()
    length = int(resp_headers.get("content-length", 0))
    data = await reader.readexactly(length) if length else b""
    writer.close()
    if raw:
        return status, resp_headers, data
    return status, (json.loads(data) if data else None)


@contextlib.asynccontextmanager
async def rest_instance():
    rt = ServiceRuntime(InstanceSettings(instance_id="rest", rest_port=0))
    for cls in (InstanceManagementService, DeviceManagementService,
                AssetManagementService, EventSourcesService,
                InboundProcessingService, EventManagementService,
                DeviceStateService, RuleProcessingService,
                DeviceRegistrationService, CommandDeliveryService,
                OutboundConnectorsService, BatchOperationsService,
                ScheduleManagementService, LabelGenerationService):
        rt.add_service(cls(rt))
    await rt.start()
    port = rt.services["instance-management"].rest.port
    try:
        yield rt, port
    finally:
        await rt.stop()


def test_jwt_roundtrip_and_authz(run):
    async def main():
        async with rest_instance() as (rt, port):
            # no auth → 401
            status, body = await http(port, "GET", "/api/tenants")
            assert status == 401
            # bad credentials → 401
            status, _ = await http(port, "POST", "/api/jwt",
                                   basic="admin:wrong")
            assert status == 401
            # good credentials → token
            status, body = await http(port, "POST", "/api/jwt",
                                      basic="admin:password")
            assert status == 200
            token = body["token"]
            # token works
            status, body = await http(port, "GET", "/api/tenants", token=token)
            assert status == 200 and body == []
            # health requires no auth (k8s-liveness parity)
            status, body = await http(port, "GET", "/api/instance/health")
            assert status == 200 and body["status"] == "started"
            # tampered token → 401
            status, _ = await http(port, "GET", "/api/tenants",
                                   token=token[:-4] + "AAAA")
            assert status == 401

    run(main())


def test_jwt_expiry():
    tm = TokenManagement("secret", expiration_s=3600)
    t = tm.issue("u", ("REST",), expiration_s=-10)
    assert tm.validate(t) is None
    t2 = tm.issue("u", ("REST",))
    ctx = tm.validate(t2)
    assert ctx.username == "u" and ctx.has_authority("REST")
    assert TokenManagement("other").validate(t2) is None


def test_full_rest_device_lifecycle(run):
    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]

            # create tenant (engines spin across services)
            status, tenant = await http(
                port, "POST", "/api/tenants", token=tok,
                body={"token": "acme", "name": "Acme",
                      "sections": {"rule-processing": {"model": None}}})
            assert status == 200 and tenant["token"] == "acme"
            # duplicate → 409
            status, _ = await http(port, "POST", "/api/tenants", token=tok,
                                   body={"token": "acme"})
            assert status == 409

            # device type + command + device
            status, dt = await http(
                port, "POST", "/api/devicetypes", token=tok, tenant="acme",
                body={"token": "thermo", "name": "Thermometer"})
            assert status == 200
            status, cmd = await http(
                port, "POST", "/api/devicetypes/thermo/commands", token=tok,
                tenant="acme", body={"token": "reboot", "name": "reboot"})
            assert status == 200
            status, device = await http(
                port, "POST", "/api/devices", token=tok, tenant="acme",
                body={"token": "dev-1", "deviceType": "thermo"})
            assert status == 200 and device["index"] == 0

            # ingest one measurement via REST → flows the whole pipeline
            status, r = await http(
                port, "POST", "/api/assignments/dev-1-a/measurements",
                token=tok, tenant="acme",
                body={"value": 21.5, "eventDate": 1000.0})
            assert status == 200 and r["accepted"] == 1

            async def measurement_visible():
                s, ms = await http(
                    port, "GET", "/api/assignments/dev-1-a/measurements",
                    token=tok, tenant="acme")
                return s == 200 and len(ms) == 1 and ms[0]["value"] == 21.5

            for _ in range(100):
                if await measurement_visible():
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("measurement never visible")

            # device state materialized
            status, st = await http(port, "GET", "/api/devices/dev-1/state",
                                    token=tok, tenant="acme")
            assert status == 200 and st["last_seen"] == 1000.0

            # command invocation → delivery
            status, inv = await http(
                port, "POST", "/api/assignments/dev-1-a/invocations",
                token=tok, tenant="acme",
                body={"commandToken": "reboot",
                      "parameterValues": {"delay": 1}})
            assert status == 200
            delivery = rt.api("command-delivery").delivery("acme")
            await wait_until(
                lambda: delivery.providers["queue"].inbox("dev-1"))

            # label renders as SVG
            status, headers, svg = await http(
                port, "GET", "/api/labels/devices/dev-1", token=tok,
                tenant="acme", raw=True)
            assert status == 200
            assert headers["content-type"] == "image/svg+xml"
            assert svg.startswith(b"<svg")

            # unknown tenant → 404; missing header → 400
            status, _ = await http(port, "GET", "/api/devices", token=tok,
                                   tenant="ghost")
            assert status == 404
            status, _ = await http(port, "GET", "/api/devices", token=tok)
            assert status == 400

    run(main())


def test_rest_script_upload_hot_reload(run):
    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]
            await http(port, "POST", "/api/tenants", token=tok,
                       body={"token": "acme",
                             "sections": {"rule-processing": {"model": None}}})
            # syntax error rejected at upload
            status, err = await http(
                port, "PUT", "/api/scripts/bad", token=tok, tenant="acme",
                body={"source": "def process(:"})
            assert status == 400
            # non-async rejected
            status, _ = await http(
                port, "PUT", "/api/scripts/sync", token=tok, tenant="acme",
                body={"source": "def process(event, api):\n    pass"})
            assert status == 400
            # good script installs as a hook
            src = ("counted = []\n"
                   "async def process(event, api):\n"
                   "    counted.append(type(event).__name__)\n")
            status, s1 = await http(
                port, "PUT", "/api/scripts/counter", token=tok, tenant="acme",
                body={"source": src})
            assert status == 200 and s1["version"] == 1
            engine = rt.api("rule-processing").engine("acme")
            assert "script:counter" in engine.hooks
            # update → version bumps, hook replaced
            status, s2 = await http(
                port, "PUT", "/api/scripts/counter", token=tok, tenant="acme",
                body={"source": src + "# v2\n"})
            assert s2["version"] == 2
            # list + delete
            status, scripts = await http(port, "GET", "/api/scripts",
                                         token=tok, tenant="acme")
            assert [s["name"] for s in scripts] == ["counter"]
            await http(port, "DELETE", "/api/scripts/counter", token=tok,
                       tenant="acme")
            assert "script:counter" not in engine.hooks

    run(main())


def test_rest_batch_and_training(run):
    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]
            await http(port, "POST", "/api/tenants", token=tok,
                       body={"token": "acme",
                             "sections": {"rule-processing": {"model": None}}})
            await http(port, "POST", "/api/devicetypes", token=tok,
                       tenant="acme", body={"token": "t", "name": "T"})
            await http(port, "POST", "/api/devicetypes/t/commands", token=tok,
                       tenant="acme", body={"token": "ping", "name": "ping"})
            for i in range(3):
                await http(port, "POST", "/api/devices", token=tok,
                           tenant="acme",
                           body={"token": f"d{i}", "deviceType": "t"})
            status, op = await http(
                port, "POST", "/api/batch/command", token=tok, tenant="acme",
                body={"deviceTokens": ["d0", "d1", "d2"],
                      "commandToken": "ping", "deviceTypeId": ""})
            assert status == 200

            async def done():
                s, o = await http(port, "GET", f"/api/batch/{op['id']}",
                                  token=tok, tenant="acme")
                return o["processing_status"] == "finished"

            for _ in range(200):
                if await done():
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("batch op never finished")
            status, elements = await http(
                port, "GET", f"/api/batch/{op['id']}/elements", token=tok,
                tenant="acme")
            assert len(elements) == 3

    run(main())


def test_rest_trace_endpoints(run):
    """Pipeline spans are queryable over REST [SURVEY.md §5.1]."""

    async def main():
        async with rest_instance() as (rt, port):
            rt.tracer.sample = 1
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]
            status, _ = await http(
                port, "POST", "/api/tenants", token=tok,
                body={"token": "acme", "name": "Acme",
                      "sections": {"rule-processing": {"model": None}}})
            assert status == 200
            # push a few payloads through the pipeline
            from sitewhere_tpu.domain.model import DeviceType
            from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig
            rt.api("device-management").management("acme").bootstrap_fleet(
                DeviceType(token="thermo", name="T"), 10)
            sim = DeviceSimulator(SimConfig(num_devices=10), tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme").receiver("default")
            for k in range(5):
                await receiver.submit(sim.payload(t=60.0 * k)[0])
            em = rt.api("event-management").management("acme")
            from tests.test_pipeline import wait_until
            await wait_until(lambda: em.telemetry.total_events == 50)

            status, summary = await http(port, "GET", "/api/instance/traces",
                                         token=tok)
            assert status == 200
            assert "event-sources.decode" in summary
            status, spans = await http(
                port, "GET", "/api/instance/traces/spans?stage=inbound.enrich",
                token=tok)
            assert status == 200 and spans["spans"]
            tid = spans["spans"][0]["trace_id"]
            status, journey = await http(
                port, "GET", f"/api/instance/traces/{tid}", token=tok)
            assert status == 200
            stages = [s["stage"] for s in journey["spans"]]
            assert stages[0] == "event-sources.receive"
            assert stages[1] == "event-sources.decode"

    run(main())


def test_rest_device_groups_crud_and_expand(run):
    """VERDICT gap: /api/devicegroups CRUD + elements + recursive
    expansion over the REST surface."""

    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            token = body["token"]
            await http(port, "POST", "/api/tenants", token=token,
                       body={"token": "acme",
                             "sections": {"rule-processing": {"model": None}}})
            dm = rt.api("device-management").management("acme")
            from sitewhere_tpu.domain.model import DeviceType

            dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 5)

            st, g = await http(port, "POST", "/api/devicegroups",
                               token=token, tenant="acme",
                               body={"token": "floor-1", "name": "Floor 1",
                                     "roles": ["monitoring"]})
            assert st == 200 and g["token"] == "floor-1"
            st, nested = await http(port, "POST", "/api/devicegroups",
                                    token=token, tenant="acme",
                                    body={"token": "rack-a"})
            assert st == 200
            st, els = await http(
                port, "POST", "/api/devicegroups/rack-a/elements",
                token=token, tenant="acme",
                body={"elements": [{"device": "dev-0"},
                                   {"device": "dev-1"}]})
            assert st == 200 and len(els) == 2
            st, els = await http(
                port, "POST", "/api/devicegroups/floor-1/elements",
                token=token, tenant="acme",
                body={"elements": [{"device": "dev-4"},
                                   {"group": "rack-a"}]})
            assert st == 200
            # recursive expansion: dev-4 + rack-a's two devices
            st, devices = await http(port, "GET",
                                     "/api/devicegroups/floor-1/devices",
                                     token=token, tenant="acme")
            assert st == 200
            assert sorted(d["token"] for d in devices) == \
                ["dev-0", "dev-1", "dev-4"]
            st, groups = await http(port, "GET", "/api/devicegroups",
                                    token=token, tenant="acme")
            assert st == 200 and len(groups) == 2
            st, _ = await http(port, "DELETE", "/api/devicegroups/rack-a",
                               token=token, tenant="acme")
            assert st == 200
            st, _ = await http(port, "GET", "/api/devicegroups/rack-a",
                               token=token, tenant="acme")
            assert st == 404
            # unknown element refs are 400, not 500
            st, _ = await http(
                port, "POST", "/api/devicegroups/floor-1/elements",
                token=token, tenant="acme",
                body={"elements": [{"device": "nope"}]})
            assert st == 400

    run(main())


def test_rest_qr_label_scannable(run):
    """VERDICT gap: QR symbology beside Code 39 — and the symbol must
    ACTUALLY scan (decoded with OpenCV's QR reader)."""

    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            token = body["token"]
            await http(port, "POST", "/api/tenants", token=token,
                       body={"token": "acme",
                             "sections": {"rule-processing": {"model": None}}})
            from sitewhere_tpu.domain.model import DeviceType

            dm = rt.api("device-management").management("acme")
            dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 3)
            st, headers, svg = await http(
                port, "GET", "/api/labels/devices/dev-2?generator=qr",
                token=token, tenant="acme", raw=True)
            assert st == 200
            assert headers["content-type"] == "image/svg+xml"
            assert b"<svg" in svg and b"path" in svg

            import cv2
            import numpy as np

            from sitewhere_tpu.services.qrcode import qr_matrix

            M = np.array(qr_matrix(b"dev-2"), dtype=np.uint8)
            img = (np.pad(1 - M, 4, constant_values=1) * 255).astype(np.uint8)
            img = np.kron(img, np.ones((8, 8), np.uint8)).astype(np.uint8)
            data, _, _ = cv2.QRCodeDetector().detectAndDecode(img)
            assert data == "dev-2"

    run(main())


def test_rest_templated_tenant_scores_without_bootstrap(run):
    """VERDICT gap: POST /api/tenants {template: "demo"} seeds
    types/fleet/group/scripts — the tenant scores simulator events with
    NO manual bootstrap."""

    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            token = body["token"]
            st, t = await http(port, "POST", "/api/tenants", token=token,
                               body={"token": "acme", "template": "demo"})
            assert st == 200 and t["token"] == "acme"
            dm = rt.api("device-management").management("acme")
            assert dm.get_device_type_by_token("thermo") is not None
            assert dm.get_device_by_token("dev-99") is not None  # 100 fleet
            group = dm.get_device_group_by_token("demo-floor-1")
            assert group is not None
            assert len(dm.expand_group_devices(group.id)) == 10
            am = rt.api("asset-management").management("acme")
            assert am.get_asset_by_token("hvac-1") is not None
            rp = rt.api("rule-processing").engine("acme")
            assert "script:high-temp-note" in rp.hooks
            assert rp.session is not None  # streaming scorer configured

            # unknown template is a clean 409/400-class error
            st, err = await http(port, "POST", "/api/tenants", token=token,
                                 body={"token": "b", "template": "nope"})
            assert st == 409

            # the templated tenant scores events end to end
            from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

            session = rp.session
            t0 = asyncio.get_event_loop().time()
            while not session.ready:
                await asyncio.sleep(0.1)
                assert asyncio.get_event_loop().time() - t0 < 120
            receiver = rt.api("event-sources").engine("acme") \
                .receiver("default")
            sim = DeviceSimulator(SimConfig(num_devices=100), tenant_id="acme")
            for k in range(3):
                await receiver.submit(sim.payload(t=60.0 * k)[0])
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 300)
            snap = rt.metrics.snapshot()
            await wait_until(lambda: rt.metrics.snapshot()
                             ["scoring.e2e_latency_s"]["count"] >= 300)

    run(main())


def test_rest_decoder_script_upload(run):
    """Decoder scripts (event-sources extension surface) upload, list,
    hot-reload, and delete over REST with the scripts authority."""

    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]
            await http(port, "POST", "/api/tenants", token=tok,
                       body={"token": "acme",
                             "sections": {"rule-processing": {"model": None}}})
            src = ("def decode(payload, ctx):\n"
                   "    tok, val = payload.decode().split(',')\n"
                   "    return [{'type': 'measurement', 'device': tok,\n"
                   "             'value': float(val)}]\n")
            status, s1 = await http(
                port, "PUT", "/api/decoder-scripts/csv", token=tok,
                tenant="acme", body={"source": src})
            assert status == 200 and s1["version"] == 1
            # async entrypoint is NOT acceptable for a decoder
            # (rejected at upload, not at first event)
            status, _ = await http(
                port, "PUT", "/api/decoder-scripts/bad", token=tok,
                tenant="acme",
                body={"source": "async def decode(p, c):\n    return []"})
            assert status == 400
            # the uploaded script is usable by a new receiver,
            # created over REST (dynamic source management)
            status, r = await http(
                port, "POST", "/api/eventsources/receivers", token=tok,
                tenant="acme", body={"kind": "queue",
                                     "decoder": "script:csv",
                                     "name": "csv"})
            assert status == 200 and r["name"] == "csv"
            status, rs = await http(
                port, "GET", "/api/eventsources/receivers", token=tok,
                tenant="acme")
            assert "csv" in [x["name"] for x in rs]
            # duplicate name and unknown decoder are client errors
            status, _ = await http(
                port, "POST", "/api/eventsources/receivers", token=tok,
                tenant="acme", body={"kind": "queue", "name": "csv"})
            assert status == 409
            status, _ = await http(
                port, "POST", "/api/eventsources/receivers", token=tok,
                tenant="acme", body={"kind": "queue",
                                     "decoder": "script:nope",
                                     "name": "x"})
            assert status == 400
            # a receiver whose start() fails (port already in use) must
            # not squat its name: creation 400s AND the name is free
            blocker = await asyncio.start_server(
                lambda r, w: None, "127.0.0.1", 0)
            taken = blocker.sockets[0].getsockname()[1]
            status, _ = await http(
                port, "POST", "/api/eventsources/receivers", token=tok,
                tenant="acme", body={"kind": "tcp", "name": "t1",
                                     "port": taken})
            blocker.close()
            assert status == 400
            status, rs = await http(
                port, "GET", "/api/eventsources/receivers", token=tok,
                tenant="acme")
            assert "t1" not in [x["name"] for x in rs]
            engine = rt.api("event-sources").engine("acme")
            status, scripts = await http(port, "GET", "/api/decoder-scripts",
                                         token=tok, tenant="acme")
            assert [s["name"] for s in scripts] == ["csv"]
            # deleting while a live receiver references it → 409, kept
            status, err = await http(port, "DELETE",
                                     "/api/decoder-scripts/csv",
                                     token=tok, tenant="acme")
            assert status == 409 and "in use" in err["error"]
            # unbind the receiver OVER REST, then delete succeeds
            status, _ = await http(
                port, "DELETE", "/api/eventsources/receivers/csv",
                token=tok, tenant="acme")
            assert status == 200
            status, _ = await http(
                port, "DELETE", "/api/eventsources/receivers/csv",
                token=tok, tenant="acme")
            assert status == 404
            status, _ = await http(port, "DELETE",
                                   "/api/decoder-scripts/csv",
                                   token=tok, tenant="acme")
            assert status == 200
            status, scripts = await http(port, "GET", "/api/decoder-scripts",
                                         token=tok, tenant="acme")
            assert scripts == []

    run(main())


def test_rest_full_event_type_surface(run):
    """Every reference event type has REST create+query parity:
    location (pipeline path), alert, command invocation → response
    (correlated by originating event id), state change."""

    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]
            await http(port, "POST", "/api/tenants", token=tok,
                       body={"token": "acme",
                             "sections": {"rule-processing": {"model": None}}})
            await http(port, "POST", "/api/devicetypes", token=tok,
                       tenant="acme", body={"token": "thermo", "name": "T"})
            await http(port, "POST", "/api/devices", token=tok,
                       tenant="acme",
                       body={"token": "dev-1", "deviceType": "thermo"})

            # location: through the real pipeline (decoded-events topic)
            status, r = await http(
                port, "POST", "/api/assignments/dev-1-a/locations",
                token=tok, tenant="acme",
                body={"latitude": 47.3, "longitude": 8.5,
                      "elevation": 410.0, "eventDate": 2000.0})
            assert status == 200 and r["accepted"] == 1
            for _ in range(100):
                s, locs = await http(
                    port, "GET", "/api/assignments/dev-1-a/locations",
                    token=tok, tenant="acme")
                if s == 200 and len(locs) == 1:
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("location never visible")
            assert abs(locs[0]["latitude"] - 47.3) < 1e-9
            # non-numeric coordinates are the client's error (400), not
            # a poisoned persister loop
            status, err = await http(
                port, "POST", "/api/assignments/dev-1-a/locations",
                token=tok, tenant="acme", body={"latitude": "north"})
            assert status == 400
            status, _ = await http(
                port, "POST", "/api/assignments/dev-1-a/alerts",
                token=tok, tenant="acme", body={"level": 2})
            assert status == 400

            # operator alert
            status, alert = await http(
                port, "POST", "/api/assignments/dev-1-a/alerts",
                token=tok, tenant="acme",
                body={"type": "overheat", "message": "too hot",
                      "level": "warning"})
            assert status == 200 and alert["level"] == "warning"
            status, alerts = await http(
                port, "GET", "/api/assignments/dev-1-a/alerts",
                token=tok, tenant="acme")
            assert [a["type"] for a in alerts] == ["overheat"]
            status, _ = await http(
                port, "POST", "/api/assignments/dev-1-a/alerts",
                token=tok, tenant="acme", body={"level": "nope"})
            assert status == 400

            # invocation → response, correlated
            status, cmd = await http(
                port, "POST", "/api/devicetypes/thermo/commands",
                token=tok, tenant="acme",
                body={"token": "reboot", "name": "reboot"})
            status, inv = await http(
                port, "POST", "/api/assignments/dev-1-a/invocations",
                token=tok, tenant="acme", body={"commandToken": "reboot"})
            assert status == 200
            status, invs = await http(
                port, "GET", "/api/assignments/dev-1-a/invocations",
                token=tok, tenant="acme")
            assert [i["id"] for i in invs] == [inv["id"]]
            status, resp = await http(
                port, "POST", "/api/assignments/dev-1-a/responses",
                token=tok, tenant="acme",
                body={"originatingEventId": inv["id"], "response": "ok"})
            assert status == 200
            status, resps = await http(
                port, "GET", f"/api/invocations/{inv['id']}/responses",
                token=tok, tenant="acme")
            assert [r["response"] for r in resps] == ["ok"]
            # responses for an unknown invocation → empty, not error
            status, none = await http(
                port, "GET", "/api/invocations/nope/responses",
                token=tok, tenant="acme")
            assert status == 200 and none == []

            # state change
            status, sc = await http(
                port, "POST", "/api/assignments/dev-1-a/statechanges",
                token=tok, tenant="acme",
                body={"attribute": "firmware", "previousState": "1.0",
                      "newState": "1.1"})
            assert status == 200
            status, scs = await http(
                port, "GET", "/api/assignments/dev-1-a/statechanges",
                token=tok, tenant="acme")
            assert [c["new_state"] for c in scs] == ["1.1"]

            # missing-device query: dev-1 last reported at ts 2000
            status, missing = await http(
                port, "GET",
                "/api/devicestates/missing?olderThan=1000&now=5000",
                token=tok, tenant="acme")
            assert status == 200 and [m["token"] for m in missing] == ["dev-1"]
            status, missing = await http(
                port, "GET",
                "/api/devicestates/missing?olderThan=9000&now=5000",
                token=tok, tenant="acme")
            assert missing == []

    run(main())


def test_rest_device_forecast(run):
    """GET /api/devices/{token}/forecast surfaces the model plane's
    forecast (config 3): TFT returns [H, Q] quantiles in original
    units, LSTM a 1-step point forecast, zscore 404s."""

    async def main():
        from sitewhere_tpu.domain.model import DeviceType
        from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]
            await http(port, "POST", "/api/tenants", token=tok,
                       body={"token": "acme", "sections": {
                           "rule-processing": {
                               "model": "tft",
                               "model_config": {"window": 16, "horizon": 4,
                                                "hidden": 8},
                               "buckets": [32], "capacity": 32}}})
            await http(port, "POST", "/api/tenants", token=tok,
                       body={"token": "zs", "sections": {
                           "rule-processing": {"model": "zscore",
                                               "model_config": {"window": 8},
                                               "buckets": [32]}}})
            for t in ("acme", "zs"):
                dm = rt.api("device-management").management(t)
                dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 4)
                em = rt.api("event-management").management(t)
                sim = DeviceSimulator(SimConfig(num_devices=4, seed=1),
                                      tenant_id=t)
                for k in range(20):
                    em.telemetry.append_measurements(sim.tick(t=60.0 * k)[0])

            status, fc = await http(
                port, "GET", "/api/devices/dev-1/forecast",
                token=tok, tenant="acme")
            assert status == 200, fc
            assert fc["horizon"] == 4 and fc["quantiles"] == [0.1, 0.5, 0.9]
            assert len(fc["forecast"]) == 4
            assert all(len(step) == 3 for step in fc["forecast"])
            med = fc["forecast"][0][1]
            assert 0.0 < med < 60.0     # original units, plausible range
            assert fc["history_points"] == 12  # context only: horizon tail unobserved
            assert "attention" not in fc
            status, fc2 = await http(
                port, "GET", "/api/devices/dev-1/forecast?attention=true",
                token=tok, tenant="acme")
            assert status == 200
            attn = fc2["attention"]      # [heads, H, W]
            assert len(attn[0]) == 4 and len(attn[0][0]) == 16
            import math
            assert all(math.isfinite(w) for w in attn[0][0])

            # zscore has no forecast surface
            status, err = await http(
                port, "GET", "/api/devices/dev-1/forecast",
                token=tok, tenant="zs")
            assert status == 404 and "no forecast" in err["error"]

            # pooled tenant (shared stacked params): LSTM point forecast
            await http(port, "POST", "/api/tenants", token=tok,
                       body={"token": "pl", "sections": {
                           "rule-processing": {
                               "model": "lstm-stream",
                               "model_config": {"window": 16},
                               "buckets": [32], "shared": True}}})
            dm = rt.api("device-management").management("pl")
            dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 4)
            em = rt.api("event-management").management("pl")
            sim = DeviceSimulator(SimConfig(num_devices=4, seed=2),
                                  tenant_id="pl")
            for k in range(20):
                em.telemetry.append_measurements(sim.tick(t=60.0 * k)[0])
            status, fc = await http(
                port, "GET", "/api/devices/dev-2/forecast",
                token=tok, tenant="pl")
            assert status == 200, fc
            assert fc["horizon"] == 1 and fc["quantiles"] == [0.5]
            assert 0.0 < fc["forecast"][0][0] < 60.0
            # LSTM has forecast but no attention: explicit 404
            status, err = await http(
                port, "GET", "/api/devices/dev-2/forecast?attention=1",
                token=tok, tenant="pl")
            assert status == 404 and "attention" in err["error"]

    run(main())


def test_openapi_description(run):
    """GET /api/openapi.json: unauthenticated machine-readable spec
    covering every installed route, with path params converted and JWT
    authorities annotated (the reference's Swagger analog)."""

    async def main():
        async with rest_instance() as (rt, port):
            status, spec = await http(port, "GET", "/api/openapi.json")
            assert status == 200
            assert spec["openapi"].startswith("3.")
            n_ops = sum(len(v) for v in spec["paths"].values())
            assert n_ops >= 85, n_ops
            # regex named groups became {param} path templates
            tenant = spec["paths"]["/api/tenants/{token}"]["get"]
            assert tenant["parameters"][0]["name"] == "token"
            # authorities annotated; auth-free routes carry no security
            users = spec["paths"]["/api/users"]["get"]
            assert users["x-authority"] == "ADMINISTER_USERS"
            assert "security" not in spec["paths"]["/api/jwt"]["post"]
            # spec covers the whole live route table
            assert n_ops == len(rt.services["instance-management"]
                                .rest._routes)

    run(main())


def test_shutdown_with_live_keepalive_connection(run):
    """A client holding a keep-alive connection (normal HTTP behavior)
    must not wedge instance shutdown: 3.12's wait_closed() waits for
    handlers, so stop() closes tracked client writers first. Found by
    a kill/restart drive whose instance needed SIGKILL."""

    async def main():
        rt = ServiceRuntime(InstanceSettings(instance_id="ka",
                                             rest_port=0))
        rt.add_service(InstanceManagementService(rt))
        await rt.start()
        port = rt.services["instance-management"].rest.port
        # one full request/response, then HOLD the connection open
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /api/instance/health HTTP/1.1\r\n"
                     b"Host: x\r\n\r\n")
        await writer.drain()
        await reader.readuntil(b"\r\n\r\n")
        # do NOT close: stop must still finish promptly
        await asyncio.wait_for(rt.stop(), 10)
        writer.close()

    run(main())
