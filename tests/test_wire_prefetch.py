"""Wire data-plane fast-path tests (kernel/wire.py, ISSUE 14).

Three layers under test: streaming poll prefetch (broker-push deliver
frames under a credit window), pipelined micro-batched produce (per-tick
multi-op batch frames with a bounded fire-and-forget window), and the
zero-copy codec path — plus the equivalence re-runs the fast path must
not bend: the fleet kill drill and the straddle exactly-once invariant
from tests/test_fleet.py over a REAL wire broker with prefetch on, and
prefetch-on/off scored-output equivalence over the wire."""

import asyncio

import numpy as np

from sitewhere_tpu.kernel.bus import EventBus
from sitewhere_tpu.kernel.wire import BusServer, RemoteEventBus
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_fleet import DEVICES, _crash, _Meter, fleet
from tests.test_pipeline import wait_until


# ---------------------------------------------------------------------------
# prefetch protocol (no jax, cheap)
# ---------------------------------------------------------------------------


def test_prefetch_streams_without_poll_rpcs(run):
    """With prefetch on, records reach the client through pushed
    deliver frames — the broker sees subscribe/commit/credit ops but
    not one poll RPC per consumer round."""

    async def main():
        bus = EventBus(default_partitions=2)
        server = BusServer(bus)
        polls = 0
        orig = server._op_poll

        async def counting_poll(msg, writer=None):
            nonlocal polls
            polls += 1
            return await orig(msg, writer)

        server.handlers["poll"] = counting_poll
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port,
                                prefetch=True, prefetch_credit=32)
        await remote.initialize()
        consumer = remote.subscribe("t", group="g")
        for i in range(20):
            await remote.produce("t", {"i": i}, key=f"k{i % 3}")
        got = []
        while len(got) < 20:
            got += [r.value["i"]
                    for r in await consumer.poll(max_records=8,
                                                 timeout=2.0)]
        assert sorted(got) == list(range(20))
        assert polls == 0, "prefetch mode still issued poll RPCs"
        # long-poll latency: a produce lands in the prefetch buffer
        # without the client asking
        async def later():
            await asyncio.sleep(0.05)
            await remote.produce("t", {"i": 99})

        t = asyncio.get_running_loop().create_task(later())
        t0 = asyncio.get_running_loop().time()
        records = await consumer.poll(max_records=10, timeout=5.0)
        waited = asyncio.get_running_loop().time() - t0
        await t
        assert [r.value["i"] for r in records] == [99]
        assert waited < 1.0
        consumer.close()
        await remote.stop()
        await server.stop()

    run(main())


def test_prefetch_credit_window_bounds_delivery(run):
    """The broker may push at most the granted credit ahead of the
    consumer's drain; draining re-grants and the stream continues."""

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port,
                                prefetch=True, prefetch_credit=8)
        await remote.initialize()
        consumer = remote.subscribe("t", group="g")
        # bind the subscription, then flood far past the window
        await consumer.poll(max_records=1, timeout=0.2)
        for i in range(64):
            await remote.produce("t", {"i": i})
        await asyncio.sleep(0.3)
        assert len(consumer._buf) <= 8, (
            f"broker pushed {len(consumer._buf)} records past an "
            f"8-record credit window")
        got = []
        while len(got) < 64:
            batch = await consumer.poll(max_records=16, timeout=2.0)
            assert batch, f"stream stalled at {len(got)}/64"
            got += [r.value["i"] for r in batch]
        assert got == list(range(64))
        consumer.close()
        await remote.stop()
        await server.stop()

    run(main())


def test_prefetch_kill_mid_credit_window_loses_nothing(run):
    """THE kill-drill property at the wire layer: a consumer killed
    (socket dropped, no reconnect, no final commits) with a full credit
    window in flight — some records drained+committed, some drained but
    uncommitted, some still in the prefetch buffer — hands a successor
    exactly every record past the last commit: nothing lost, nothing
    committed-and-replayed."""

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port,
                                prefetch=True, prefetch_credit=16)
        await remote.initialize()
        for i in range(50):
            await remote.produce("t", {"i": i})
        consumer = remote.subscribe("t", group="g")
        drained = []
        while len(drained) < 20:
            drained += [r.value["i"] for r in await consumer.poll(
                max_records=min(5, 20 - len(drained)), timeout=2.0)]
        assert drained == list(range(20))
        consumer.commit()  # pins delivered-through: offset 20
        # let the commit batch land, then SIGKILL the client with the
        # credit window mid-flight (buffer holds undrained records)
        await asyncio.sleep(0.2)
        remote._client.kill()
        await asyncio.sleep(0.1)  # broker reaps the dropped peer
        successor_bus = RemoteEventBus("127.0.0.1", server.port,
                                       prefetch=True, prefetch_credit=16)
        await successor_bus.initialize()
        successor = successor_bus.subscribe("t", group="g")
        redelivered = []
        while len(redelivered) < 30:
            batch = await successor.poll(max_records=16, timeout=2.0)
            assert batch, (f"successor stalled at {len(redelivered)}/30: "
                           f"records lost in the killed credit window")
            redelivered += [r.value["i"] for r in batch]
        # exactly the uncommitted suffix, in order: no loss, no replay
        # of the committed prefix
        assert redelivered == list(range(20, 50))
        successor.close()
        await successor_bus.stop()
        await server.stop()

    run(main())


def test_prefetch_revoke_on_rebalance_no_double_delivery(run):
    """A rebalance revokes the credit window: the first member's
    undrained buffer is dropped (those records re-deliver from
    committed offsets) — the group as a whole sees every record, and
    the moved partitions never double-deliver through a stale window."""

    async def main():
        bus = EventBus(default_partitions=4)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port,
                                prefetch=True, prefetch_credit=64)
        await remote.initialize()
        c1 = remote.subscribe("t", group="g")
        await c1.poll(max_records=1, timeout=0.2)  # bind + start push
        for i in range(40):
            await remote.produce("t", {"i": i}, key=f"k{i}")
        # the whole topic fits the credit window: wait until every
        # record sits undrained in c1's buffer
        await wait_until(lambda: len(c1._buf) == 40, timeout=10.0)
        # second member joins: rebalance moves half the partitions.
        # Do NOT drain c1 until its revoke lands — the revoke is what
        # prevents its stale 40-row window from double-delivering
        # beside the post-rebalance re-deliveries.
        c2 = remote.subscribe("t", group="g")
        got1, got2 = [], []
        got2 += [r.value["i"]
                 for r in await c2.poll(max_records=64, timeout=2.0)]
        await wait_until(lambda: len(c1._buf) < 40, timeout=10.0)
        deadline = asyncio.get_event_loop().time() + 10.0
        while (len(got1) + len(got2) < 40
               and asyncio.get_event_loop().time() < deadline):
            got1 += [r.value["i"]
                     for r in await c1.poll(max_records=16, timeout=0.2)]
            got2 += [r.value["i"]
                     for r in await c2.poll(max_records=16, timeout=0.2)]
        # nothing drained before the rebalance and nothing committed →
        # the union must be exactly-once across the member set
        assert sorted(got1 + got2) == list(range(40)), (
            f"double/lost delivery across rebalance: "
            f"{len(got1)}+{len(got2)}")
        c1.close()
        c2.close()
        await remote.stop()
        await server.stop()

    run(main())


def test_prefetch_seek_to_beginning_replays_cleanly(run):
    """A replay consumer (seek-from-beginning, the hermetic-adoption
    path) over prefetch sees the topic exactly once from offset 0 —
    rows pushed before the seek are revoked, not mixed in."""

    async def main():
        bus = EventBus(default_partitions=2)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port,
                                prefetch=True, prefetch_credit=32)
        await remote.initialize()
        for i in range(10):
            await remote.produce("t", {"i": i}, key=f"k{i}")
        # group with committed progress: a fresh member would resume at
        # the committed offsets, a seeking member must NOT
        warm = remote.subscribe("t", group="g")
        got = []
        while len(got) < 10:
            got += [r.value["i"]
                    for r in await warm.poll(max_records=16, timeout=2.0)]
        warm.commit()
        await asyncio.sleep(0.2)
        warm.close()
        await asyncio.sleep(0.1)
        replayer = remote.subscribe("t", group="g")
        replayer.seek_to_beginning()  # before first poll: rides subscribe
        replayed = []
        while len(replayed) < 10:
            batch = await replayer.poll(max_records=16, timeout=2.0)
            assert batch, f"replay stalled at {len(replayed)}/10"
            replayed += [r.value["i"] for r in batch]
        assert sorted(replayed) == list(range(10))
        # and a mid-stream seek replays again without mixing
        replayer.seek_to_beginning()
        again = []
        while len(again) < 10:
            batch = await replayer.poll(max_records=16, timeout=2.0)
            assert batch, f"re-replay stalled at {len(again)}/10"
            again += [r.value["i"] for r in batch]
        assert sorted(again) == list(range(10)), again
        replayer.close()
        await remote.stop()
        await server.stop()

    run(main())


# ---------------------------------------------------------------------------
# pipelined micro-batched produce + the bounded fire-and-forget window
# ---------------------------------------------------------------------------


def test_produce_nowait_coalesces_per_tick(run):
    """N produce_nowait calls in one event-loop tick ride ONE multi-op
    batch frame (no task per op), and every record lands."""

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)
        batches = []
        orig = server._op_batch

        async def counting_batch(msg, writer=None):
            batches.append(len(msg["ops"]))
            return await orig(msg, writer)

        server.handlers["batch"] = counting_batch
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port)
        await remote.initialize()
        for i in range(32):
            remote.produce_nowait("t", {"i": i})
        assert len(remote._client._bg) <= 1, (
            "produce_nowait spawned per-op tasks")
        await wait_until(lambda: bus.end_offsets("t") == [32], timeout=5.0)
        assert max(batches) >= 16, (
            f"ops did not coalesce per tick: batch sizes {batches}")
        assert remote.wire_stats()["frames_coalesced"] >= 16
        await remote.stop()
        await server.stop()

    run(main())


def test_ff_inflight_cap_backpressure_gated_broker(run):
    """SATELLITE regression: against a gated (stalled) broker, the
    fire-and-forget window fills to the cap and `backlogged` turns on —
    no per-op task growth, no unbounded socket writes — and once the
    broker resumes every op lands and the signal clears."""

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)
        gate = asyncio.Event()
        orig = server._op_batch

        async def gated_batch(msg, writer=None):
            await gate.wait()
            return await orig(msg, writer)

        server.handlers["batch"] = gated_batch
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port,
                                inflight_cap=16)
        await remote.initialize()
        assert remote.backlogged is False
        for i in range(100):
            remote.produce_nowait("t", {"i": i})
            await asyncio.sleep(0)  # let ticks flush
        await asyncio.sleep(0.1)
        client = remote._client
        assert remote.backlogged is True
        assert client._ff_inflight <= 16, (
            f"{client._ff_inflight} un-acked ops past a 16-op cap")
        # task growth is bounded by the CAP (one ack-handler task per
        # in-flight batch frame), never by the op count — the old
        # task-per-op design would sit at 100 here
        assert len(client._bg) <= 16, (
            f"stalled broker grew {len(client._bg)} background tasks")
        assert client.ff_pending == 100  # nothing dropped
        gate.set()
        await wait_until(lambda: bus.end_offsets("t") == [100],
                         timeout=10.0)
        await wait_until(lambda: not remote.backlogged, timeout=5.0)
        assert client.ff_pending == 0
        await remote.stop()
        await server.stop()

    run(main())


def test_ff_order_preserved_vs_awaited_frames(run):
    """A fire-and-forget op enqueued BEFORE an awaited produce reaches
    the broker first (the commit-before-release ordering the handoff
    protocol needs), even though the batch frame is assembled at flush
    time."""

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port)
        await remote.initialize()
        remote.produce_nowait("t", {"seq": 0})       # queued this tick
        await remote.produce("t", {"seq": 1})        # same tick, awaited
        await wait_until(lambda: bus.end_offsets("t") == [2], timeout=5.0)
        values = [r.value["seq"] for r in bus.peek("t", limit=-1)]
        assert values == [0, 1], (
            f"awaited frame overtook a queued fire-and-forget op: "
            f"{values}")
        await remote.stop()
        await server.stop()

    run(main())


def test_egress_barrier_surfaces_wire_backpressure():
    """The egress stage folds the wire client's fire-and-forget window
    into its commit-barrier `backlogged` — the consumer-pause signal."""

    class _Bus:
        backlogged = True
        produce_nowait = None

    class _Runtime:
        bus = _Bus()

    from sitewhere_tpu.kernel.egresslane import EgressStage

    stage = object.__new__(EgressStage)
    stage.engine = type("E", (), {"runtime": _Runtime()})()
    stage.submitted = 0
    stage.accounted = 0
    stage.active = 1
    assert stage.backlogged is True
    _Bus.backlogged = False
    assert stage.backlogged is False


# ---------------------------------------------------------------------------
# fleet equivalence re-runs over the wire (prefetch on)
# ---------------------------------------------------------------------------


def test_fleet_kill_drill_wire_prefetch_zero_loss(run, tmp_path):
    """tests/test_fleet.py's kill drill over a REAL wire broker with
    prefetch on: the victim dies with a credit window mid-flight
    (socket dropped, no final commits) — reassignment converges and
    every accepted event is scored by somebody (zero loss)."""

    async def main():
        async with fleet(tmp_path, n_workers=2, n_tenants=2,
                         wire=True) as (
                driver, controller, runtimes, workers, cfgs):
            meter = _Meter(driver, cfgs)
            for _ in range(3):
                await meter.submit_round()
            await meter.drain_until_caught_up()

            victim = controller.snapshot()["assignment"]["t0"]
            survivor = next(w for w in workers if w != victim)
            # keep accepting through the crash + reassignment window so
            # the killed credit window has live records in it
            await meter.submit_round()
            await _crash(runtimes, workers, victim)
            for _ in range(4):
                await meter.submit_round()
                await asyncio.sleep(0.05)
            await wait_until(
                lambda: victim not in controller.snapshot()["workers"],
                timeout=30.0)
            await wait_until(
                lambda: controller.snapshot()["converged"], timeout=120.0)
            snap = controller.snapshot()
            assert all(w == survivor for w in snap["assignment"].values())
            for _ in range(2):
                await meter.submit_round()
            await meter.drain_until_caught_up(timeout=120.0)
            # zero lost accepted events (at-least-once: >= is the bound
            # a crash permits; the straddle test pins == for the clean
            # handoff)
            for tid in meter.sent:
                assert meter.scored[tid] >= meter.sent[tid], (
                    tid, meter.sent[tid], meter.scored[tid])
            meter.close()

    run(main())


def test_fleet_straddle_exactly_once_wire_prefetch(run, tmp_path):
    """tests/test_fleet.py's straddle invariant over the wire with
    prefetch on: a clean drain-then-handoff migration under continuous
    flood lands every batch EXACTLY once — the loser's delivered-pin
    commit covers only drained records, its undrained prefetch buffer
    is discarded at close, and the adopter resumes from committed."""

    async def main():
        async with fleet(tmp_path, n_workers=2, n_tenants=2,
                         wire=True) as (
                driver, controller, runtimes, workers, cfgs):
            meter = _Meter(driver, cfgs)
            await meter.submit_round()
            await meter.drain_until_caught_up()

            source = controller.snapshot()["assignment"]["t0"]
            target = next(w for w in workers if w != source)
            controller.migrate("t0", target)
            for _ in range(12):
                await meter.submit_round()
                await asyncio.sleep(0.02)
            await wait_until(
                lambda: controller.snapshot()["owners"].get("t0")
                == target and controller.snapshot()["converged"],
                timeout=60.0)
            for _ in range(2):
                await meter.submit_round()
            await meter.drain_until_caught_up(timeout=120.0)
            # exactly once: scored == sent (< is loss, > is duplicate)
            for tid in meter.sent:
                assert meter.scored[tid] == meter.sent[tid], (
                    tid, meter.sent[tid], meter.scored[tid])
            meter.close()

    run(main())


def test_prefetch_on_off_scored_output_equivalence(run, tmp_path):
    """The fast path must not bend a single score: the same simulator
    traffic through a 1-worker wire fleet produces IDENTICAL scored
    tuples with prefetch/pipelining on and off."""

    async def one_leg(leg_dir, fast):
        outputs = []
        async with fleet(leg_dir, n_workers=1, n_tenants=1,
                         wire=True, wire_prefetch=fast,
                         wire_pipeline=fast) as (
                driver, controller, runtimes, workers, cfgs):
            tid = cfgs[0].tenant_id
            consumer = driver.bus.subscribe(
                driver.naming.tenant_topic(tid, "scored-events"),
                group="equiv-meter")
            receiver = driver.api("event-sources").engine(tid) \
                .receiver("default")
            sim = DeviceSimulator(SimConfig(num_devices=DEVICES, seed=11),
                                  tenant_id=tid)
            sent = 0
            for k in range(6):
                if await receiver.submit(sim.payload(t=3000.0 + k)[0]):
                    sent += DEVICES

            def caught_up():
                for record in consumer.poll_nowait(max_records=256):
                    scored = record.value
                    for i in range(len(scored)):
                        outputs.append((
                            int(scored.device_index[i]),
                            round(float(scored.score[i]), 5),
                            bool(scored.is_anomaly[i])))
                return len(outputs) >= sent

            await wait_until(caught_up, timeout=90.0)
            consumer.close()
        return sorted(outputs)

    async def main():
        on = await one_leg(tmp_path / "on", True)
        off = await one_leg(tmp_path / "off", False)
        assert len(on) == len(off) > 0
        assert on == off, "prefetch changed scored output"

    run(main())


# ---------------------------------------------------------------------------
# zero-copy delivery sanity
# ---------------------------------------------------------------------------


def test_prefetch_delivers_zero_copy_views(run):
    """Delivered batch columns are read-only views over the received
    frame (the zero-copy decode path), and their contents are exact."""
    from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port,
                                prefetch=True, prefetch_credit=8)
        await remote.initialize()
        ctx = BatchContext(tenant_id="t", source="s", trace_id=7)
        values = np.linspace(0.0, 1.0, 4096).astype(np.float32)
        batch = MeasurementBatch(
            ctx, np.arange(4096, dtype=np.uint32),
            np.zeros(4096, np.uint16), values,
            np.full(4096, 1700000000.0))
        await remote.produce("t", batch, key="s")
        consumer = remote.subscribe("t", group="g")
        records = []
        while not records:
            records = await consumer.poll(max_records=4, timeout=2.0)
        out = records[0].value
        np.testing.assert_array_equal(out.value, values)
        assert out.ctx.tenant_id == "t" and out.ctx.trace_id == 7
        # the column is a view over the frame, not a copy
        assert out.value.base is not None
        assert not out.value.flags.writeable
        consumer.close()
        await remote.stop()
        await server.stop()

    run(main())


# -- push-loop supervision (swx lint TSK01 regression) -----------------------


def test_push_loop_death_is_supervised(run, caplog):
    """An unexpected escape from a prefetch push loop is logged — the
    pre-fix task died silently, wedging the consumer's credit window
    with no traceback anywhere."""
    import logging

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)

        async def doomed(cid, consumer, writer, st):
            raise RuntimeError("push loop exploded")

        server._push_loop = doomed
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port,
                                prefetch=True, prefetch_credit=8)
        await remote.initialize()
        consumer = remote.subscribe("t", group="g")
        await consumer.poll(max_records=1, timeout=0.3)  # forces subscribe
        await asyncio.sleep(0.05)
        consumer.close()
        await remote.stop()
        await server.stop()

    with caplog.at_level(logging.ERROR, logger="sitewhere_tpu.kernel.wire"):
        run(main())
    assert any("died unexpectedly" in r.getMessage()
               for r in caplog.records)
