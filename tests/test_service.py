"""Service runtime + tenant engine lifecycle tests [SURVEY.md §3.1, §3.5]."""

import asyncio

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.kernel.lifecycle import LifecycleStatus
from sitewhere_tpu.kernel.service import (
    Service,
    ServiceRuntime,
    TenantEngine,
)


class EchoEngine(TenantEngine):
    async def _do_start(self, monitor):
        self.started_for = self.tenant_id


class EchoService(Service):
    identifier = "echo"
    multitenant = True

    def create_tenant_engine(self, tenant):
        return EchoEngine(self, tenant)


class GlobalService(Service):
    identifier = "global"


def test_runtime_starts_services_and_engines(run):
    async def main():
        rt = ServiceRuntime(InstanceSettings(instance_id="test"))
        echo = rt.add_service(EchoService(rt))
        rt.add_service(GlobalService(rt))
        await rt.start()
        assert rt.status == LifecycleStatus.STARTED

        await rt.add_tenant(TenantConfig(tenant_id="acme"))
        engine = echo.engine("acme")
        assert engine.status == LifecycleStatus.STARTED
        assert engine.started_for == "acme"
        assert engine.tenant_topic("inbound-events") == \
            "test.tenant.acme.inbound-events"

        # update restarts the engine (fresh instance)
        await rt.update_tenant(TenantConfig(tenant_id="acme", name="Acme v2"))
        engine2 = echo.engine("acme")
        assert engine2 is not engine
        assert engine2.tenant.name == "Acme v2"

        await rt.remove_tenant("acme")
        assert "acme" not in echo.engines
        await rt.stop()
        assert rt.status == LifecycleStatus.STOPPED

    run(main())


def test_engines_bootstrap_for_preexisting_tenants(run):
    async def main():
        rt = ServiceRuntime(InstanceSettings(instance_id="test"))
        rt.tenants["pre"] = TenantConfig(tenant_id="pre")
        echo = rt.add_service(EchoService(rt))
        await rt.start()
        # engine manager bootstraps tenants known before start
        for _ in range(200):
            if "pre" in echo.engines and \
                    echo.engines["pre"].status == LifecycleStatus.STARTED:
                break
            await asyncio.sleep(0.01)
        assert echo.engine("pre").status == LifecycleStatus.STARTED
        await rt.stop()

    run(main())


def test_api_and_wait_for_api(run):
    async def main():
        rt = ServiceRuntime(InstanceSettings(instance_id="test"))
        rt.add_service(GlobalService(rt))
        await rt.start()
        api = await rt.wait_for_api("global")
        assert api is rt.services["global"]
        await rt.stop()

    run(main())


def test_add_tenant_creates_engine_exactly_once(run):
    """The manager's bootstrap scan and the tenant-model-updates broadcast
    race on a freshly added tenant; the engine must be built once, not
    created-then-replaced (a replaced engine's consumers can leak group
    membership and starve the data plane — regression)."""

    async def main():
        rt = ServiceRuntime(InstanceSettings(instance_id="once"))
        echo = rt.add_service(EchoService(rt))
        created = []
        orig = EchoService.create_tenant_engine

        def counting(self, tenant):
            engine = orig(self, tenant)
            created.append(engine)
            return engine

        EchoService.create_tenant_engine = counting
        try:
            await rt.start()
            await rt.add_tenant(TenantConfig(tenant_id="acme"))
            await asyncio.sleep(0.3)  # let any late broadcast record land
            assert len(created) == 1, f"engine created {len(created)}x"
            assert echo.engine("acme") is created[0]
            # a real config update must still spin a fresh engine
            await rt.update_tenant(TenantConfig(tenant_id="acme", name="v2"))
            assert len(created) == 2
        finally:
            EchoService.create_tenant_engine = orig
            await rt.stop()

    run(main())


def test_tenant_consumer_groups_have_single_member(run):
    """Every per-tenant consumer group ends with exactly one live member
    after startup (a stale second member keeps partitions assigned and
    silently drops that topic's traffic — regression for the
    rule-processing subscribe/cancellation leak)."""

    async def main():
        from sitewhere_tpu.services import (
            DeviceManagementService,
            DeviceStateService,
            EventManagementService,
            EventSourcesService,
            InboundProcessingService,
            RuleProcessingService,
        )

        rt = ServiceRuntime(InstanceSettings(instance_id="grp"))
        for cls in (DeviceManagementService, EventSourcesService,
                    InboundProcessingService, EventManagementService,
                    DeviceStateService, RuleProcessingService):
            rt.add_service(cls(rt))
        await rt.start()
        await rt.add_tenant(TenantConfig(tenant_id="acme", sections={
            "rule-processing": {"model": "zscore",
                                "model_config": {"window": 32}}}))
        await asyncio.sleep(0.3)
        for group, state in rt.bus._groups.items():
            if group.startswith("acme."):
                assert len(state.members) == 1, \
                    f"group {group} has {len(state.members)} members"
        await rt.stop()

    run(main())


def test_example_instance_yaml_boots(run):
    """examples/instance.yaml is living documentation: it must load and
    boot a full runtime with every configured surface (receivers,
    scripted decoder, pooled + dedicated scorers, presence, geofence,
    webhook connector) coming up healthy."""

    async def main():
        import os

        from sitewhere_tpu.config import load_yaml_config

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "examples", "instance.yaml")
        settings, tenants = load_yaml_config(path)
        assert settings.instance_id == "example"
        assert [t.tenant_id for t in tenants] == ["factory", "sensors"]

        import dataclasses

        from sitewhere_tpu.cli import _build_runtime

        # ephemeral ports for the test run (the yaml pins real ones)
        settings = dataclasses.replace(settings, rest_port=0)
        for t in tenants:
            for rc in t.sections["event-sources"]["receivers"] \
                    if "event-sources" in t.sections else []:
                if "port" in rc:
                    rc["port"] = 0
        rt = _build_runtime(settings, [])
        await rt.start()
        try:
            for t in tenants:
                await rt.add_tenant(t)
            src = rt.api("event-sources").engine("factory")
            assert {r.name for r in src.receivers} >= {
                "default", "gateway", "mqtt", "coap", "json-in"}
            assert src.decoder_scripts.get("csv") is not None
            rp = rt.api("rule-processing").engine("factory")
            assert rp.session is not None          # dedicated scorer
            assert "geofence" in rp.hooks and "script:audit" in rp.hooks
            assert rt.api("device-state").state("factory").presence \
                is not None
            oc = rt.api("outbound-connectors").engine("factory")
            assert "ops-hook" in oc.connectors
            rp2 = rt.api("rule-processing").engine("sensors")
            assert rp2.pool_slot is not None       # pooled scorer
        finally:
            await rt.stop()

    run(main())


def test_cli_split_validation():
    """`swx run --services/--remote` misconfigurations fail loudly at
    startup (colocation constraints, unsupported remotes, unused
    remotes) rather than misbehaving at runtime."""
    import pytest

    from sitewhere_tpu.cli import _validate_split

    # rule-processing needs event-management + device-state colocated
    with pytest.raises(SystemExit, match="colocated"):
        _validate_split({"rule-processing"}, None)
    # a valid scorer-process split passes
    _validate_split({"device-management", "inbound-processing",
                     "event-management", "device-state",
                     "rule-processing"}, None)
    # a service can't be both local and remote
    with pytest.raises(SystemExit, match="both local"):
        _validate_split({"device-management", "inbound-processing"},
                        {"device-management": ("h", 1)})
    # only wire-aware identifiers may be remote
    with pytest.raises(SystemExit, match="not supported"):
        _validate_split({"inbound-processing"},
                        {"event-sources": ("h", 1)})
    # a remote nobody consumes is a config error, not silence
    with pytest.raises(SystemExit, match="unused"):
        _validate_split({"event-sources"},
                        {"device-management": ("h", 1)})
    # the supported remote with its consumer passes
    _validate_split({"inbound-processing"},
                    {"device-management": ("h", 1)})
    # no --services means ALL services local: any --remote collides
    with pytest.raises(SystemExit, match="conflicts"):
        _validate_split(None, {"device-management": ("h", 1)})
    _validate_split(None, None)
    _validate_split(None, {})
