"""Tests for the full service roster: registration, command delivery,
outbound connectors, batch operations (incl. training trigger),
schedules, labels [SURVEY.md §2.2 parity]."""

import asyncio
import contextlib
import json
import time

import numpy as np

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.events import DeviceCommandInvocation
from sitewhere_tpu.domain.model import (
    BatchOperationStatus,
    DeviceCommand,
    DeviceType,
    Schedule,
    ScheduledJob,
)
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.services import (
    AssetManagementService,
    BatchOperationsService,
    CommandDeliveryService,
    DeviceManagementService,
    DeviceRegistrationService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
    LabelGenerationService,
    OutboundConnectorsService,
    RuleProcessingService,
    ScheduleManagementService,
)
from sitewhere_tpu.services.schedule_management import cron_matches
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_pipeline import wait_until


@contextlib.asynccontextmanager
async def full_instance(sections: dict | None = None, num_devices: int = 20,
                        tmp_path=None):
    sections = dict(sections or {})
    sections.setdefault("rule-processing", {
        "model": "zscore", "model_config": {"window": 16},
        "batch_window_ms": 1.0, "buckets": [256]})
    if tmp_path is not None:
        sections.setdefault("batch-operations",
                            {"checkpoint_root": str(tmp_path / "ckpt")})
    rt = ServiceRuntime(InstanceSettings(instance_id="full"))
    for cls in (DeviceManagementService, AssetManagementService,
                EventSourcesService, InboundProcessingService,
                EventManagementService, DeviceStateService,
                RuleProcessingService, DeviceRegistrationService,
                CommandDeliveryService, OutboundConnectorsService,
                BatchOperationsService, ScheduleManagementService,
                LabelGenerationService):
        rt.add_service(cls(rt))
    await rt.start()
    await rt.add_tenant(TenantConfig(tenant_id="acme", sections=sections))
    dm = rt.api("device-management").management("acme")
    dt = DeviceType(token="thermo", name="Thermometer")
    dm.bootstrap_fleet(dt, num_devices)
    try:
        yield rt
    finally:
        await rt.stop()


def test_auto_registration_via_json(run):
    async def main():
        sections = {"device-registration": {
            "allow_unknown_devices": True,
            "default_device_type": "auto-type"}}
        async with full_instance(sections) as rt:
            sources = rt.api("event-sources").engine("acme")
            sources.add_receiver({"kind": "queue", "decoder": "json",
                                  "name": "json-in"})
            await sources.receiver("json-in").start()
            payload = json.dumps({"requests": [
                {"type": "registration", "device": "new-dev-1",
                 "deviceType": "auto-type"},
                {"type": "measurement", "device": "never-seen", "value": 5.0},
            ]}).encode()
            await sources.receiver("json-in").submit(payload)

            dm = rt.api("device-management").management("acme")
            await wait_until(
                lambda: dm.get_device_by_token("new-dev-1") is not None)
            await wait_until(
                lambda: dm.get_device_by_token("never-seen") is not None)
            # auto-registered device got an active assignment
            d = dm.get_device_by_token("new-dev-1")
            assert dm.get_active_assignments_for_device(d.id)
            # redelivery is idempotent
            await sources.receiver("json-in").submit(payload)
            await asyncio.sleep(0.1)
            assert len([x for x in dm.list_devices(page_size=1000)
                        if x.token == "new-dev-1"]) == 1

    run(main())


def test_command_delivery_roundtrip(run):
    async def main():
        async with full_instance() as rt:
            dm = rt.api("device-management").management("acme")
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="reboot", device_type_id=dt.id, name="reboot",
                parameters=(("delay_s", "int64", False),)))
            device = dm.get_device_by_token("dev-3")
            assignment = dm.get_active_assignments_for_device(device.id)[0]

            em = rt.api("event-management").management("acme")
            inv = DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id, parameter_values={"delay_s": 5})
            await em.add_command_invocations([inv])

            delivery = rt.api("command-delivery").delivery("acme")
            provider = delivery.providers["queue"]
            await wait_until(lambda: provider.inbox("dev-3"))
            msg = json.loads(provider.inbox("dev-3")[0])
            assert msg["command"] == "reboot"
            assert msg["parameters"] == {"delay_s": 5}
            # invocation is also persisted + queryable (reference parity)
            assert em.list_command_invocations()[0].id == inv.id

    run(main())


def test_outbound_connectors_filtering(run, tmp_path):
    async def main():
        sections = {"outbound-connectors": {"connectors": [
            {"kind": "memory", "name": "all"},
            {"kind": "memory", "name": "only-anomalies", "kinds": ["scored"],
             "min_score": 4.0},
            {"kind": "jsonl", "name": "export",
             "path": str(tmp_path / "out.jsonl"), "kinds": ["measurements"]},
        ]}}
        async with full_instance(sections, num_devices=50) as rt:
            sim = DeviceSimulator(SimConfig(num_devices=50, seed=5),
                                  tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme").receiver("default")
            for k in range(20):
                await receiver.submit(sim.payload(t=60.0 * k)[0])
            sim.cfg = SimConfig(num_devices=50, seed=5, anomaly_rate=0.2,
                                anomaly_magnitude=15.0)
            payload, truth = sim.payload(t=21 * 60.0)
            await receiver.submit(payload)

            engine = rt.api("outbound-connectors").engine("acme")
            anomalies = engine.connectors["only-anomalies"]
            await wait_until(lambda: anomalies.records, timeout=15.0)
            assert all(r.score.min() >= 4.0 for r in anomalies.records)
            assert engine.connectors["all"].records

            # the jsonl exporter is an independent consumer group — its
            # progress is not ordered against the anomaly connector's,
            # so wait for it on its own terms
            def jsonl_lines():
                try:
                    return (tmp_path / "out.jsonl").read_text() \
                        .strip().splitlines()
                except FileNotFoundError:
                    return []

            await wait_until(lambda: len(jsonl_lines()) >= 20, timeout=15.0)
            lines = jsonl_lines()
            assert json.loads(lines[0])["kind"] == "measurements"

    run(main())


def test_batch_command_operation(run):
    async def main():
        async with full_instance(num_devices=25) as rt:
            dm = rt.api("device-management").management("acme")
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="ping", device_type_id=dt.id, name="ping"))
            devices = dm.list_devices(page_size=100)
            batch = rt.api("batch-operations").operations("acme")
            op = await batch.submit_command_operation(
                [d.id for d in devices], cmd.id)
            op = await batch.wait_for_operation(op.id, timeout=30.0)
            assert op.processing_status == BatchOperationStatus.FINISHED_SUCCESSFULLY
            elements = batch.list_batch_elements(op.id)
            assert len(elements) == 25
            assert all(e.processing_status.value == "succeeded"
                       for e in elements)
            # every device got its command delivered
            provider = rt.api("command-delivery").delivery("acme").providers["queue"]
            await wait_until(lambda: len(provider.delivered) == 25)

    run(main())


def test_training_operation_trains_checkpoints_and_hot_swaps(run, tmp_path):
    async def main():
        sections = {"rule-processing": {
            "model": "lstm", "model_config": {"window": 16, "hidden": 8},
            "batch_window_ms": 1.0, "buckets": [256]}}
        async with full_instance(sections, num_devices=30,
                                 tmp_path=tmp_path) as rt:
            em = rt.api("event-management").management("acme")
            sim = DeviceSimulator(SimConfig(num_devices=30, seed=2),
                                  tenant_id="acme")
            # history straight into the store (training data)
            for k in range(200):
                em.telemetry.append_measurements(sim.tick(t=60.0 * k)[0])

            rule_engine = rt.api("rule-processing").engine("acme")
            v0 = rule_engine.session.version
            batch = rt.api("batch-operations").operations("acme")
            op = await batch.submit_training_operation(
                "lstm", steps=30, batch_size=64)
            op = await batch.wait_for_operation(op.id, timeout=120.0)
            assert op.processing_status == BatchOperationStatus.FINISHED_SUCCESSFULLY
            result = op.parameters["result"]
            assert result["windows"] > 0
            assert result["losses"][-1] < result["losses"][0]
            assert result["hot_swapped"] is True
            assert rule_engine.session.version == v0 + 1

            # checkpoint is on disk and loadable
            from sitewhere_tpu.training.checkpoint import CheckpointStore
            store = CheckpointStore(str(tmp_path / "ckpt"))
            params, meta = store.load("acme", "lstm")
            assert meta["version"] == result["checkpoint_version"]
            assert "head" in params

    run(main())


def test_schedule_fires_command(run):
    async def main():
        async with full_instance() as rt:
            dm = rt.api("device-management").management("acme")
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="beep", device_type_id=dt.id, name="beep"))
            device = dm.get_device_by_token("dev-0")
            sched = rt.api("schedule-management").schedules("acme")
            sched.tick_s = 0.05
            s = sched.create_schedule(Schedule(
                name="every-tick", trigger_type="simple",
                trigger_configuration={"repeat_interval_s": 0.1,
                                       "repeat_count": 2}))
            sched.create_scheduled_job(ScheduledJob(
                schedule_id=s.id, job_type="command-invocation",
                configuration={"device_id": device.id, "command_id": cmd.id}))
            provider = rt.api("command-delivery").delivery("acme").providers["queue"]
            await wait_until(lambda: len(provider.inbox("dev-0")) >= 2,
                             timeout=10.0)
            # repeat_count=2 → at most 3 fires (first + 2 repeats)
            await asyncio.sleep(0.3)
            assert len(provider.inbox("dev-0")) <= 3

    run(main())


def test_cron_matcher():
    from datetime import datetime

    assert cron_matches("* * * * *", datetime(2026, 7, 29, 10, 30))
    assert cron_matches("*/15 * * * *", datetime(2026, 7, 29, 10, 30))
    assert not cron_matches("*/15 * * * *", datetime(2026, 7, 29, 10, 31))
    assert cron_matches("30 10 * * *", datetime(2026, 7, 29, 10, 30))
    assert not cron_matches("30 11 * * *", datetime(2026, 7, 29, 10, 30))
    assert cron_matches("0 0 29 7 *", datetime(2026, 7, 29, 0, 0))
    # 2026-07-29 is a Wednesday → POSIX cron dow 3 (0=Sunday)
    assert cron_matches("* * * * 3", datetime(2026, 7, 29, 5, 0))
    assert not cron_matches("* * * * 2", datetime(2026, 7, 29, 5, 0))
    # Sunday matches both 0 and 7 (2026-08-02 is a Sunday)
    assert cron_matches("* * * * 0", datetime(2026, 8, 2, 5, 0))
    assert cron_matches("* * * * 7", datetime(2026, 8, 2, 5, 0))


def test_label_generation(run):
    async def main():
        async with full_instance() as rt:
            labels = rt.api("label-generation").labels("acme")
            svg = labels.device_label("dev-7").decode()
            assert svg.startswith("<svg")
            assert "DEV-7" in svg          # token text
            assert svg.count("<rect") > 20  # barcode bars
            from sitewhere_tpu.services.label_generation import code39_svg
            bars_a, _ = code39_svg("AAA")
            bars_b, _ = code39_svg("BBB")
            assert bars_a != bars_b

    run(main())


def test_chaos_service_restart_mid_stream(run):
    """Failure-recovery fixture [SURVEY.md §5.3]: kill + restart a
    mid-pipeline service while events flow; at-least-once semantics mean
    everything sent is eventually persisted."""

    async def main():
        async with full_instance(num_devices=40) as rt:
            sim = DeviceSimulator(SimConfig(num_devices=40), tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme").receiver("default")
            em_service = rt.services["event-management"]

            for k in range(5):
                await receiver.submit(sim.payload(t=100.0 + k)[0])
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events >= 200)

            # kill event-management mid-stream
            await em_service.stop()
            for k in range(5):
                await receiver.submit(sim.payload(t=200.0 + k)[0])
            await asyncio.sleep(0.2)

            # restart: engine rebuilds, consumer resumes from committed
            # offsets, the 5 in-flight batches are persisted
            await em_service.initialize()
            await em_service.start()
            await wait_until(
                lambda: "acme" in em_service.engines
                and em_service.engines["acme"].spi is not None
                and em_service.engines["acme"].telemetry.total_events >= 200,
                timeout=15.0)
            em2 = rt.api("event-management").management("acme")
            await wait_until(lambda: em2.telemetry.total_events == 200,
                             timeout=15.0)

    run(main())


def test_webhook_and_mqtt_republish_connectors(run):
    """Round-4 VERDICT item 5: REAL outbound connectors. An external
    HTTP endpoint (fake server) and an external MQTT subscriber (raw
    socket through the broker endpoint) both receive an enriched scored
    record, with filter composition (kind + min_score) and webhook
    retry-through-failure exercised end to end."""

    async def main():
        from tests.test_mqtt import connect_pkt, read_pkt, subscribe_pkt

        hits: list = []
        fail_first = [2]  # first two POSTs fail → retry/backoff path

        async def handle(reader, writer):
            try:
                head = await reader.readuntil(b"\r\n\r\n")
                length = 0
                for line in head.decode("latin-1").split("\r\n"):
                    if line.lower().startswith("content-length"):
                        length = int(line.split(":")[1])
                body = await reader.readexactly(length)
                if fail_first[0] > 0:
                    fail_first[0] -= 1
                    writer.write(b"HTTP/1.1 500 Oops\r\n"
                                 b"Content-Length: 0\r\n\r\n")
                else:
                    hits.append(json.loads(body))
                    writer.write(b"HTTP/1.1 200 OK\r\n"
                                 b"Content-Length: 0\r\n\r\n")
                await writer.drain()
            finally:
                writer.close()

        http_server = await asyncio.start_server(handle, "127.0.0.1", 0)
        http_port = http_server.sockets[0].getsockname()[1]
        sections = {
            "event-sources": {"receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "mqtt", "decoder": "swb1", "name": "mqtt",
                 "subscribe_allow": ["swx/outbound/"]}]},
            "outbound-connectors": {"connectors": [
                {"kind": "webhook", "name": "wh",
                 "url": f"http://127.0.0.1:{http_port}/ingest",
                 "kinds": ["scored"], "min_score": 4.0, "backoff_s": 0.05},
                {"kind": "mqtt", "name": "mq", "receiver": "mqtt",
                 "kinds": ["scored"], "min_score": 4.0}]},
        }
        try:
            async with full_instance(sections, num_devices=30) as rt:
                mqtt_port = (rt.api("event-sources").engine("acme")
                             .receiver("mqtt").port)
                # external dashboard subscribes to the outbound space
                r, w = await asyncio.open_connection("127.0.0.1", mqtt_port)
                w.write(connect_pkt("dashboard"))
                await w.drain()
                ptype, _, _ = await read_pkt(r)
                assert ptype == 2  # CONNACK
                w.write(subscribe_pkt("swx/outbound/#"))
                await w.drain()
                ptype, _, body = await read_pkt(r)
                assert ptype == 9 and body[2] != 0x80  # SUBACK granted

                sim = DeviceSimulator(SimConfig(num_devices=30, seed=5),
                                      tenant_id="acme")
                receiver = (rt.api("event-sources").engine("acme")
                            .receiver("default"))
                for k in range(20):
                    await receiver.submit(sim.payload(t=60.0 * k)[0])
                sim.cfg = SimConfig(num_devices=30, seed=5, anomaly_rate=0.3,
                                    anomaly_magnitude=15.0)
                payload, truth = sim.payload(t=21 * 60.0)
                await receiver.submit(payload)

                # webhook: retried through the two 500s, then delivered
                # only scored records with score >= 4.0
                await wait_until(lambda: hits, timeout=20.0)
                assert hits[0]["kind"] == "scored"
                assert min(hits[0]["score"]) >= 4.0
                engine = rt.api("outbound-connectors").engine("acme")
                assert engine.connectors["wh"].delivered >= 1
                assert engine.connectors["wh"].dead_lettered == 0
                assert fail_first[0] == 0  # the retry path actually ran

                # MQTT: the external subscriber received the republish
                ptype, _, body = await asyncio.wait_for(read_pkt(r), 10.0)
                assert ptype == 3  # PUBLISH
                tlen = int.from_bytes(body[:2], "big")
                assert body[2:2 + tlen] == b"swx/outbound/scored"
                doc = json.loads(body[2 + tlen:])
                assert doc["kind"] == "scored"
                assert min(doc["score"]) >= 4.0
                w.close()
        finally:
            http_server.close()

    run(main())


def test_webhook_dead_letters_on_exhausted_retries(run):
    """A webhook whose endpoint is down must dead-letter the record to
    the bus (replayable), never drop it silently."""

    async def main():
        from sitewhere_tpu.kernel.bus import EventBus
        from sitewhere_tpu.services.outbound_connectors import (
            EventFilter,
            WebhookConnector,
        )

        # a port with nothing listening: connect refused instantly
        probe = await asyncio.start_server(lambda r, w: None, "127.0.0.1", 0)
        dead_port = probe.sockets[0].getsockname()[1]
        probe.close()
        await probe.wait_closed()

        bus = EventBus(default_partitions=1)
        conn = WebhookConnector(
            "wh", f"http://127.0.0.1:{dead_port}/x", bus, "dead-letter",
            EventFilter(), retries=2, backoff_s=0.01, timeout_s=1.0)
        sim = DeviceSimulator(SimConfig(num_devices=5), tenant_id="t")
        batch, _ = sim.tick(t=0.0)
        await conn.process(batch)
        assert conn.dead_lettered == 1 and conn.delivered == 0
        c = bus.subscribe("dead-letter", group="replay")
        records = await c.poll(max_records=10, timeout=2.0)
        assert len(records) == 1
        assert len(records[0].value) == len(batch)  # the record, intact

    run(main())


def test_coap_command_delivery_with_retransmit(run):
    """Commands route to a device's own CoAP server (metadata
    coap_host/coap_port): a confirmable POST lands on /commands; a
    device that drops the first CON still receives it via RFC 7252
    retransmission; a device with no CoAP endpoint fails delivery and
    the invocation lands on the undelivered topic."""

    async def main():
        from sitewhere_tpu.kernel.bus import TopicNaming
        from sitewhere_tpu.services.coap import CoapListener

        sections = {"command-delivery": {"provider": "coap",
                                         "coap_ack_timeout": 0.2}}
        async with full_instance(sections) as rt:
            got: list[bytes] = []
            drop_first = [True]

            class LossyListener(CoapListener):
                # device-side stand-in that loses the first datagram
                def datagram_received(self, data, addr):
                    if drop_first[0]:
                        drop_first[0] = False
                        return
                    super().datagram_received(data, addr)

            async def on_cmd(payload, source):
                got.append(payload)

            device_srv = LossyListener(on_cmd, path="commands")
            await device_srv.start()

            dm = rt.api("device-management").management("acme")
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="ping", device_type_id=dt.id, name="ping"))
            device = dm.get_device_by_token("dev-4")
            import dataclasses
            dm.update_device(dataclasses.replace(device, metadata={
                "coap_host": "127.0.0.1",
                "coap_port": str(device_srv.port)}))
            assignment = dm.get_active_assignments_for_device(device.id)[0]

            em = rt.api("event-management").management("acme")
            await em.add_command_invocations([DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id, parameter_values={})])
            await wait_until(lambda: got, timeout=10.0)
            assert json.loads(got[0])["command"] == "ping"
            assert drop_first[0] is False  # retransmission was exercised

            # no CoAP endpoint in metadata → undelivered record
            undelivered = rt.bus.subscribe(
                rt.naming.tenant_topic(
                    "acme", TopicNaming.UNDELIVERED_COMMANDS),
                group="t-undelivered")
            bare = dm.get_device_by_token("dev-5")
            asn = dm.get_active_assignments_for_device(bare.id)[0]
            await em.add_command_invocations([DeviceCommandInvocation(
                device_id=bare.id, assignment_id=asn.id,
                command_id=cmd.id, parameter_values={})])
            await wait_until(
                lambda: any(r.value.device_id == bare.id
                            for r in undelivered.poll_nowait(
                                max_records=16)), timeout=10.0)
            undelivered.close()
            await device_srv.stop()

    run(main())


def test_scripted_decoder_csv_and_hot_reload(run):
    """A tenant-uploaded decoder script ingests a proprietary CSV
    framing end-to-end (reference: GroovyEventDecoder parity); hot
    reloading the script changes live decoding on the next payload; a
    script without the right entrypoint is rejected at upload."""

    CSV_V1 = (
        "def decode(payload, ctx):\n"
        "    out = []\n"
        "    for line in payload.decode().strip().splitlines():\n"
        "        tok, val, ts = line.split(',')\n"
        "        out.append({'type': 'measurement', 'device': tok,\n"
        "                    'value': float(val), 'ts': float(ts)})\n"
        "    return out\n")
    # v2: values arrive in milli-units; scale them down
    CSV_V2 = CSV_V1.replace("float(val)", "float(val) / 1000.0")

    async def main():
        import pytest

        sections = {"event-sources": {
            "scripts": {"csv": CSV_V1},
            "receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "queue", "decoder": "script:csv", "name": "csv"}]}}
        async with full_instance(sections) as rt:
            sources = rt.api("event-sources").engine("acme")
            em = rt.api("event-management").management("acme")
            rx = sources.receiver("csv")
            await rx.submit(b"dev-1,21.5,1000.0\ndev-2,22.5,1000.0\n")
            await wait_until(lambda: em.telemetry.total_events == 2)
            win, valid = em.telemetry.window(np.array([1]), 4)
            assert valid[0].sum() == 1 and win[0, -1] == 21.5

            # hot reload: same receiver object, new semantics
            sources.put_decoder_script("csv", CSV_V2)
            await rx.submit(b"dev-1,21500.0,1060.0\n")
            await wait_until(lambda: em.telemetry.total_events == 3)
            win, valid = em.telemetry.window(np.array([1]), 4)
            assert valid[0].sum() == 2 and abs(win[0, -1] - 21.5) < 1e-6

            # malformed CSV → decode-failure accounting, pipeline alive
            failures = rt.metrics.snapshot().get(
                "event_sources.decode_failures", 0)
            await rx.submit(b"not,a,valid,line,count\n")
            await wait_until(
                lambda: rt.metrics.snapshot().get(
                    "event_sources.decode_failures", 0) > failures)

            # wrong entrypoint rejected at upload, old version intact
            with pytest.raises(ValueError):
                sources.put_decoder_script("csv", "def nope(): pass\n")
            assert sources.decoder_scripts.get("csv").version == 2

    run(main())


def test_presence_monitor_marks_missing_and_recovers(run):
    """Automated presence management: silent devices transition
    present→missing as persisted state-change events; a fresh event
    transitions them back. (Reference: device-state presence manager.)"""

    async def main():
        sections = {"device-state": {"presence": {
            "missing_after_s": 100.0, "check_interval_s": 0.05}}}
        async with full_instance(sections, num_devices=5) as rt:
            ds = rt.api("device-state").state("acme")
            em = rt.api("event-management").management("acme")
            sources = rt.api("event-sources").engine("acme")
            sim_clock = [1000.0]
            ds.presence._now = lambda: sim_clock[0]

            from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig
            sim = DeviceSimulator(SimConfig(num_devices=5), tenant_id="acme")
            await sources.receiver("default").submit(
                sim.payload(t=1000.0)[0])
            await wait_until(lambda: em.telemetry.total_events == 5)
            await wait_until(lambda: float(ds.last_seen[:5].min()) == 1000.0)
            await asyncio.sleep(0.2)
            assert em.list_state_changes() == []   # all present, no noise

            # clock jumps: everyone is now silent too long
            sim_clock[0] = 2000.0
            await wait_until(lambda: len(em.list_state_changes()) == 5,
                             timeout=10.0)
            changes = em.list_state_changes()
            assert {c.new_state for c in changes} == {"missing"}
            assert all(c.attribute == "presence" for c in changes)
            assert len(ds.presence.missing) == 5

            # device 2 reports again (fresh timestamp) → recovers
            batch, _ = sim.tick(t=1999.0)
            mask = batch.device_index == 2
            import dataclasses as _dc
            single = _dc.replace(
                batch, device_index=batch.device_index[mask],
                mtype=batch.mtype[mask], value=batch.value[mask],
                ts=batch.ts[mask])
            em.telemetry.append_measurements(single)
            ds.merge_measurements(single)
            await wait_until(
                lambda: any(c.new_state == "present"
                            for c in em.list_state_changes()), timeout=10.0)
            recovered = [c for c in em.list_state_changes()
                         if c.new_state == "present"]
            assert len(recovered) == 1
            assert 2 not in ds.presence.missing
            assert len(ds.presence.missing) == 4

    run(main())


def test_geofence_point_in_polygon_unit():
    from sitewhere_tpu.services.geofence import points_in_polygon

    square = ((0.0, 0.0), (0.0, 10.0), (10.0, 10.0), (10.0, 0.0))
    lat = np.array([5.0, 15.0, 0.5, 9.9, -1.0])
    lon = np.array([5.0, 5.0, 0.5, 9.9, 5.0])
    got = points_in_polygon(lat, lon, square)
    assert got.tolist() == [True, False, True, True, False]
    # concave polygon (an L): the notch is outside
    ell = ((0.0, 0.0), (0.0, 10.0), (4.0, 10.0), (4.0, 4.0),
           (10.0, 4.0), (10.0, 0.0))
    lat = np.array([2.0, 8.0, 8.0])
    lon = np.array([8.0, 8.0, 2.0])
    assert points_in_polygon(lat, lon, ell).tolist() == [True, False, True]
    # degenerate (<3 vertices): nothing is inside
    assert not points_in_polygon(lat, lon, ((0, 0), (1, 1))).any()


def test_geofence_zone_transitions_emit_alerts(run):
    """Location events crossing a zone boundary produce enter/exit
    alerts ONCE per transition (a device dwelling inside doesn't
    re-alert every tick)."""

    async def main():
        from sitewhere_tpu.domain.batch import BatchContext, LocationBatch
        from sitewhere_tpu.domain.model import Zone

        sections = {"rule-processing": {
            "model": "zscore", "model_config": {"window": 8},
            "buckets": [64], "batch_window_ms": 1.0,
            "geofences": [{"zone": "dock", "alert_on": "both",
                           "level": "error"}]}}
        async with full_instance(sections, num_devices=4) as rt:
            dm = rt.api("device-management").management("acme")
            area = dm.list_areas()[0] if dm.list_areas() else None
            dm.create_zone(Zone(token="dock", name="Dock",
                                area_id=area.id if area else "",
                                bounds=((0.0, 0.0), (0.0, 10.0),
                                        (10.0, 10.0), (10.0, 0.0))))
            em = rt.api("event-management").management("acme")
            bus = rt.bus
            topic = rt.naming.tenant_topic("acme", "outbound-enriched-events")

            def loc_batch(dev, lat, lon, ts):
                return LocationBatch(
                    BatchContext(tenant_id="acme", source="test"),
                    np.asarray(dev, np.uint32),
                    np.asarray(lat, np.float64),
                    np.asarray(lon, np.float64),
                    np.zeros(len(dev), np.float32),
                    np.asarray(ts, np.float64))

            # devices 0,1 enter; 2 stays outside
            await bus.produce(topic, loc_batch(
                [0, 1, 2], [5.0, 2.0, 50.0], [5.0, 2.0, 50.0],
                [1.0, 1.0, 1.0]))
            await wait_until(
                lambda: len([a for a in em.list_alerts()
                             if a.type == "zone.enter"]) == 2, timeout=10.0)
            # device 0 moves WITHIN the zone: no new alert
            await bus.produce(topic, loc_batch([0], [6.0], [6.0], [2.0]))
            await asyncio.sleep(0.3)
            enters = [a for a in em.list_alerts() if a.type == "zone.enter"]
            assert len(enters) == 2
            # device 0 exits
            await bus.produce(topic, loc_batch([0], [60.0], [6.0], [3.0]))
            await wait_until(
                lambda: any(a.type == "zone.exit"
                            for a in em.list_alerts()), timeout=10.0)
            exits = [a for a in em.list_alerts() if a.type == "zone.exit"]
            assert len(exits) == 1
            assert exits[0].level.name == "ERROR"
            # re-enter alerts again (transition, not state)
            await bus.produce(topic, loc_batch([0], [5.0], [5.0], [4.0]))
            await wait_until(
                lambda: len([a for a in em.list_alerts()
                             if a.type == "zone.enter"]) == 3, timeout=10.0)

    run(main())


def test_simulator_clients_drive_every_protocol(run):
    """sim/clients.py senders (the `swx simulate --protocol ...`
    machinery) deliver SWB1 through every hosted endpoint: TCP, MQTT,
    CoAP, WebSocket, AMQP — same payload, same pipeline."""

    async def main():
        from sitewhere_tpu.sim.clients import make_sender
        from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

        sections = {"event-sources": {"receivers": [
            {"kind": "queue", "decoder": "swb1", "name": "default"},
            {"kind": "tcp", "decoder": "swb1", "name": "tcp"},
            {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"},
            {"kind": "coap", "decoder": "swb1", "name": "coap"},
            {"kind": "websocket", "decoder": "swb1", "name": "websocket"},
            {"kind": "amqp", "decoder": "swb1", "name": "amqp"},
            {"kind": "stomp", "decoder": "swb1", "name": "stomp"}]}}
        async with full_instance(sections, num_devices=10) as rt:
            em = rt.api("event-management").management("acme")
            sources = rt.api("event-sources").engine("acme")
            sim = DeviceSimulator(SimConfig(num_devices=10), tenant_id="acme")
            expected = 0
            for k, proto in enumerate(
                    ("tcp", "mqtt", "coap", "websocket", "amqp", "stomp")):
                port = sources.receiver(proto).port
                sender = make_sender(proto, "127.0.0.1", port)
                await sender.connect()
                await sender.send(sim.payload(t=60.0 * k)[0])
                expected += 10
                await wait_until(
                    lambda n=expected: em.telemetry.total_events == n,
                    timeout=10.0)
                await sender.close()

    run(main())
