"""DCN/multi-host entry test (SURVEY.md §2.4/§5.8): two REAL OS
processes join a jax.distributed process group on the CPU backend, form
the same global mesh, run lockstep DP training steps, and converge to
exactly the same loss as a single-process run on identical data — the
same `initialize_distributed` + `make_global_mesh` path a v5p multi-host
job uses, minus the hardware."""

import json
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r'''
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")

from sitewhere_tpu.parallel.distributed import (
    initialize_distributed, make_global_mesh, process_info)

joined = initialize_distributed()  # SWX_* env contract
assert joined, "worker expected a coordinator"
info = process_info()
assert info["global_devices"] == 4, info   # 2 procs x 2 virtual devices

import numpy as np
from sitewhere_tpu.models import build_model
from sitewhere_tpu.training.trainer import Trainer, TrainerConfig

mesh = make_global_mesh(model=1)           # data axis = all 4 devices
model = build_model("lstm", window=16, hidden=8)
rng = np.random.default_rng(0)             # same data in every process
windows = rng.normal(10.0, 2.0, (256, 16)).astype(np.float32)
valid = np.ones_like(windows, dtype=bool)
trainer = Trainer(model, TrainerConfig(batch_size=64, steps=5, log_every=1),
                  mesh=mesh)
params, report = trainer.train(windows, valid)
print("RESULT " + json.dumps({"rank": info["process_index"],
                              "losses": report["losses"],
                              "devices": info["global_devices"]}))
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_global_mesh_matches_single_process(tmp_path):
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ,
                   SWX_COORDINATOR=f"127.0.0.1:{port}",
                   SWX_NUM_PROCESSES="2",
                   SWX_PROCESS_ID=str(rank))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER.replace("@REPO@", repo)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env))
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err.decode()[-2000:]
        for line in out.decode().splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["rank"]] = r
    assert set(results) == {0, 1}
    # SPMD: both processes computed the identical (global) losses
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["devices"] == 4

    # single-process reference on the same data: must match exactly —
    # the global mesh changes WHERE shards live, not the math
    from sitewhere_tpu.models import build_model
    from sitewhere_tpu.parallel.mesh import make_mesh
    from sitewhere_tpu.training.trainer import Trainer, TrainerConfig

    import jax

    mesh = make_mesh(model=1, devices=jax.devices()[:4])
    model = build_model("lstm", window=16, hidden=8)
    rng = np.random.default_rng(0)
    windows = rng.normal(10.0, 2.0, (256, 16)).astype(np.float32)
    valid = np.ones_like(windows, dtype=bool)
    trainer = Trainer(model, TrainerConfig(batch_size=64, steps=5,
                                           log_every=1), mesh=mesh)
    _, report = trainer.train(windows, valid)
    np.testing.assert_allclose(report["losses"], results[0]["losses"],
                               rtol=1e-5)
