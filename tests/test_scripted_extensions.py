"""Scripted outbound connectors + scripted command encoders.

Completes the reference's four Groovy hook points ([SURVEY.md §2.2]:
decoders, rule processors, outbound connectors, command encoders) —
decoders/rules landed earlier; these tests pin the last two: same
tenant script store contract, hot reload mid-stream, REST CRUD with
delete-in-use refusal.
"""

import asyncio
import json

from sitewhere_tpu.domain.events import DeviceCommandInvocation
from sitewhere_tpu.domain.model import DeviceCommand

from tests.test_pipeline import wait_until
from tests.test_services_full import full_instance

CONNECTOR_V1 = """
async def sink(record, api):
    api.state.setdefault("seen", []).append(("v1", record["kind"]))
"""

CONNECTOR_V2 = """
async def sink(record, api):
    api.state.setdefault("seen", []).append(("v2", record["kind"]))
"""

CONNECTOR_REPUBLISH = """
async def sink(record, api):
    await api.produce("custom.sink." + api.tenant_id, record)
"""

ENCODER_V1 = """
def encode(device, command, invocation):
    name = command.name if command else invocation.command_id
    return ("CSV1," + device.token + "," + name).encode()
"""

ENCODER_V2 = """
def encode(device, command, invocation):
    name = command.name if command else invocation.command_id
    return ("CSV2," + device.token + "," + name).encode()
"""


def _ingest_measurements(rt, n=8):
    from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

    sim = DeviceSimulator(SimConfig(num_devices=n), tenant_id="acme")
    em = rt.api("event-management").management("acme")

    async def tick(t):
        batch, _ = sim.tick(t=t)
        await em.runtime.bus.produce(
            em.tenant_topic("inbound-events"), batch, key="sim")
    return tick


def test_scripted_connector_e2e_and_hot_swap(run):
    """A scripted connector receives enriched records through the REAL
    outbound consumer; uploading v2 mid-stream hot-swaps the logic while
    api.state survives the reload."""
    async def main():
        sections = {"outbound-connectors": {
            "scripts": {"collect": CONNECTOR_V1},
            "connectors": [
                {"kind": "script", "name": "sc", "script": "collect",
                 "kinds": ["measurements"]},
            ]}}
        async with full_instance(sections) as rt:
            out = rt.api("outbound-connectors").engine("acme")
            conn = out.connectors["sc"]
            tick = _ingest_measurements(rt)
            await tick(1000.0)
            await wait_until(lambda: conn.api.state.get("seen"))
            assert conn.api.state["seen"][0] == ("v1", "measurements")

            # hot swap mid-stream: v2 applies to the NEXT record,
            # state survives
            out.put_connector_script("collect", CONNECTOR_V2)
            await tick(1001.0)
            await wait_until(
                lambda: ("v2", "measurements") in conn.api.state["seen"])
            assert ("v1", "measurements") in conn.api.state["seen"]

            # filtering still applies: scored records never reach it
            assert all(k == "measurements"
                       for _, k in conn.api.state["seen"])

    run(main())


def test_scripted_connector_republish(run):
    """Scripts can bridge records onto custom bus topics (the Groovy
    connector's 'forward to anything' role)."""
    async def main():
        sections = {"outbound-connectors": {
            "scripts": {"fwd": CONNECTOR_REPUBLISH},
            "connectors": [{"kind": "script", "name": "bridge",
                            "script": "fwd",
                            "kinds": ["measurements"]}]}}
        async with full_instance(sections) as rt:
            consumer = rt.bus.subscribe("custom.sink.acme", group="t")
            try:
                tick = _ingest_measurements(rt)
                await tick(1000.0)
                got = []
                for _ in range(50):
                    got += [r.value for r in
                            await consumer.poll(max_records=8,
                                                timeout=0.1)]
                    if got:
                        break
                assert got and got[0]["kind"] == "measurements"
            finally:
                consumer.close()

    run(main())


def test_connector_script_guards(run):
    """Unknown script refused at config; delete refused while in use."""
    async def main():
        sections = {"outbound-connectors": {
            "scripts": {"used": CONNECTOR_V1},
            "connectors": [{"kind": "script", "name": "sc",
                            "script": "used"}]}}
        async with full_instance(sections) as rt:
            out = rt.api("outbound-connectors").engine("acme")
            try:
                out.add_connector_config({"kind": "script", "name": "x",
                                          "script": "nope"})
                raise AssertionError("unknown script accepted")
            except ValueError:
                pass
            try:
                out.delete_connector_script("used")
                raise AssertionError("in-use delete accepted")
            except ValueError as exc:
                assert "sc" in str(exc)
            out.remove_connector("sc")
            out.delete_connector_script("used")  # now fine

    run(main())


def test_scripted_encoder_roundtrip_and_hot_swap(run):
    """A scripted encoder drives a REAL delivery round trip (invocation
    → encode → queue provider inbox); upload mid-stream re-frames the
    next delivery."""
    async def main():
        sections = {"command-delivery": {
            "scripts": {"csv": ENCODER_V1},
            "routes": {"thermo": {"encoder": "script:csv",
                                  "provider": "queue"}}}}
        async with full_instance(sections) as rt:
            dm = rt.api("device-management").management("acme")
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="reboot", device_type_id=dt.id, name="reboot"))
            device = dm.get_device_by_token("dev-3")
            assignment = dm.get_active_assignments_for_device(device.id)[0]
            em = rt.api("event-management").management("acme")
            delivery = rt.api("command-delivery").delivery("acme")
            provider = delivery.providers["queue"]

            await em.add_command_invocations([DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id)])
            await wait_until(lambda: provider.inbox("dev-3"))
            assert provider.inbox("dev-3")[0] == b"CSV1,dev-3,reboot"

            delivery.put_encoder_script("csv", ENCODER_V2)
            await em.add_command_invocations([DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id)])
            await wait_until(lambda: len(provider.inbox("dev-3")) >= 2)
            assert provider.inbox("dev-3")[1] == b"CSV2,dev-3,reboot"

    run(main())


def test_encoder_script_guards(run):
    """Routed encoder scripts can't be deleted; unknown script fails the
    route resolution loudly."""
    async def main():
        sections = {"command-delivery": {
            "scripts": {"csv": ENCODER_V1},
            "routes": {"thermo": {"encoder": "script:csv"}}}}
        async with full_instance(sections) as rt:
            delivery = rt.api("command-delivery").delivery("acme")
            try:
                delivery.delete_encoder_script("csv")
                raise AssertionError("routed delete accepted")
            except ValueError as exc:
                assert "thermo" in str(exc)
            try:
                delivery._resolve_encoder("script:ghost")
                raise AssertionError("unknown script resolved")
            except KeyError:
                pass
            del delivery.routes["thermo"]
            delivery.delete_encoder_script("csv")

    run(main())


def test_http_delivery_provider_gateway_push(run):
    """Commands route to an external HTTP gateway (the Twilio-SMS
    provider analog): URL templated per device, encoder output POSTed
    verbatim, 2xx = delivered; a refusing gateway retries then reports
    undelivered."""
    async def main():
        received = []

        async def gateway(reader, writer):
            req = await reader.readuntil(b"\r\n\r\n")
            n = 0
            for line in req.split(b"\r\n"):
                if line.lower().startswith(b"content-length:"):
                    n = int(line.split(b":")[1])
            body = await reader.readexactly(n) if n else b""
            path = req.split(b" ")[1].decode()
            received.append((path, body))
            code = b"503 Down" if path.endswith("/broken") else b"200 OK"
            writer.write(b"HTTP/1.1 " + code +
                         b"\r\nContent-Length: 0\r\n\r\n")
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(gateway, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]

        sections = {"command-delivery": {
            "http_url": f"http://127.0.0.1:{port}/sms/{{device}}",
            "http_backoff_s": 0.01,
            "routes": {"thermo": {"encoder": "json",
                                  "provider": "http"}}}}
        async with full_instance(sections) as rt:
            dm = rt.api("device-management").management("acme")
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="beep", device_type_id=dt.id, name="beep"))
            device = dm.get_device_by_token("dev-7")
            assignment = dm.get_active_assignments_for_device(device.id)[0]
            em = rt.api("event-management").management("acme")
            await em.add_command_invocations([DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id)])
            await wait_until(lambda: received)
            path, body = received[0]
            assert path == "/sms/dev-7"
            assert json.loads(body)["command"] == "beep"
            provider = (rt.api("command-delivery").delivery("acme")
                        .providers["http"])
            assert provider.delivered == 1 and provider.failed == 0

            # refusing endpoint: retries then undelivered accounting
            provider.url_template = \
                f"http://127.0.0.1:{port}/sms/{{device}}/broken"
            before = len(received)
            await em.add_command_invocations([DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id)])
            await wait_until(lambda: provider.failed == 1, timeout=10.0)
            assert len(received) - before == provider.retries
        server.close()
        await server.wait_closed()

    run(main())


def test_rest_connector_and_encoder_script_crud(run):
    """REST CRUD for both new script families + dynamic connector
    attach/detach (mirrors the receiver surface)."""
    from tests.test_rest import http, rest_instance

    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]
            await http(port, "POST", "/api/tenants", token=tok,
                       body={"token": "acme",
                             "sections": {"rule-processing":
                                          {"model": None}}})

            # connector scripts
            status, body = await http(
                port, "PUT", "/api/connector-scripts/fwd", token=tok,
                tenant="acme", body={"source": CONNECTOR_V1})
            assert status == 200 and body["version"] == 1
            status, body = await http(
                port, "PUT", "/api/connector-scripts/bad", token=tok,
                tenant="acme",
                body={"source": "def sink(r, a): pass"})  # not async
            assert status == 400
            status, scripts = await http(
                port, "GET", "/api/connector-scripts", token=tok,
                tenant="acme")
            assert status == 200 and scripts[0]["name"] == "fwd"

            # attach a scripted connector, delete-in-use refused,
            # detach, delete ok
            status, body = await http(
                port, "POST", "/api/connectors", token=tok,
                tenant="acme",
                body={"kind": "script", "name": "sc", "script": "fwd"})
            assert status == 200, body
            status, conns = await http(port, "GET", "/api/connectors",
                                       token=tok, tenant="acme")
            assert status == 200 and conns[-1]["script"] == "fwd"
            status, body = await http(
                port, "DELETE", "/api/connector-scripts/fwd", token=tok,
                tenant="acme")
            assert status == 409
            status, body = await http(
                port, "DELETE", "/api/connectors/sc", token=tok,
                tenant="acme")
            assert status == 200
            status, body = await http(
                port, "DELETE", "/api/connector-scripts/fwd", token=tok,
                tenant="acme")
            assert status == 200

            # encoder scripts
            status, body = await http(
                port, "PUT", "/api/encoder-scripts/csv", token=tok,
                tenant="acme", body={"source": ENCODER_V1})
            assert status == 200 and body["version"] == 1
            status, scripts = await http(
                port, "GET", "/api/encoder-scripts", token=tok,
                tenant="acme")
            assert status == 200 and scripts[0]["name"] == "csv"
            status, body = await http(
                port, "DELETE", "/api/encoder-scripts/csv", token=tok,
                tenant="acme")
            assert status == 200

    run(main())
