"""Fleet observability plane tests (ISSUE-13 acceptance surface).

- cross-worker trace continuity: ONE trace id whose spine (receive →
  dispatch → score → publish, including the new `wire.produce` /
  `wire.poll` broker-hop spans) crosses REAL worker processes over the
  wire bus, stitched via the per-worker ApiServer `trace` op and
  merged fleet-wide by the FleetObserver (marked `slow`: spawning
  jax-bearing processes is the tier1.sh smoke's job, not every pytest
  sweep's — `scripts/tier1.sh` runs it explicitly);
- telemetry export + fold: each worker's beat publishes onto the
  bounded instance telemetry topic; the FleetObserver merges the fleet
  critical path / lag matrix / mesh occupancy, and a LATE observer
  rebuilds the whole view from topic replay (controller-host restart);
- durable telemetry history: window/compaction/readback semantics and
  restart survival (persistence/durable.py TelemetryHistory);
- fleet-level observe-on/off scored-output equivalence;
- broker self-stats (`EventBus.stats()` + the `bus_stats` wire op);
- the TRC01 wire-boundary trace-context contract;
- `swx top` scope honesty + `swx top --fleet` rendering.
"""

import asyncio
import contextlib
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from sitewhere_tpu.cli import render_fleet_top, render_top
from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.fleet import AutoscalerPolicy, FleetController
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.persistence.durable import TelemetryHistory
from sitewhere_tpu.services import EventSourcesService
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_fleet import (
    DEVICES,
    RP_SECTION,
    _seed_registries,
    _worker_runtime,
)
from tests.test_pipeline import wait_until


# ---------------------------------------------------------------------------
# durable telemetry history (pure)
# ---------------------------------------------------------------------------


def test_history_window_semantics(tmp_path):
    h = TelemetryHistory(str(tmp_path / "tel"), window_s=10.0)
    for k in range(25):
        h.append("t0", "lag", float(k), t=1000.0 + k)
    rows = h.history("t0", "lag")
    # 25 one-second points → windows [1000, 1010, 1020); the last is
    # the OPEN window riding along as the live tail
    assert [r["window"] for r in rows] == [1000.0, 1010.0, 1020.0]
    assert rows[0] == {"tenant": "t0", "signal": "lag", "window": 1000.0,
                      "count": 10, "sum": 45.0, "min": 0.0, "max": 9.0,
                      "last": 9.0}
    # since inclusive / until exclusive on WINDOW START: exactly the
    # middle window
    mid = h.history("t0", "lag", since=1010.0, until=1020.0)
    assert len(mid) == 1 and mid[0]["window"] == 1010.0
    assert mid[0]["count"] == 10 and mid[0]["min"] == 10.0
    # limit keeps the newest rows
    assert [r["window"] for r in h.history("t0", "lag", limit=2)] \
        == [1010.0, 1020.0]
    # series listing covers open + closed series
    h.append("t1", "egress_backlog", 3.0, t=1000.0)
    assert ("t1", "egress_backlog") in h.series()
    assert h.history("t9", "lag") == []
    h.close()


def test_history_survives_restart(tmp_path):
    h = TelemetryHistory(str(tmp_path / "tel"), window_s=10.0)
    for k in range(25):
        h.append("t0", "lag", float(k), t=1000.0 + k)
    h.close()  # flushes the open window
    h2 = TelemetryHistory(str(tmp_path / "tel"), window_s=10.0)
    assert h2.replayed == 3
    rows = h2.history("t0", "lag")
    assert [r["window"] for r in rows] == [1000.0, 1010.0, 1020.0]
    assert rows[0]["count"] == 10 and rows[2]["count"] == 5
    # appends continue into the same window: rows sharing a window
    # start merge at read time (the flush-split contract)
    h2.append("t0", "lag", 100.0, t=1025.0)
    merged = h2.history("t0", "lag")
    assert [r["window"] for r in merged] == [1000.0, 1010.0, 1020.0]
    assert merged[2]["count"] == 6 and merged[2]["max"] == 100.0
    assert h2.stats()["series"] == 1
    h2.close()


# ---------------------------------------------------------------------------
# broker self-stats
# ---------------------------------------------------------------------------


def test_bus_stats_unit_and_wire_op(run):
    async def main():
        from sitewhere_tpu.kernel.bus import EventBus
        from sitewhere_tpu.kernel.wire import BusServer, RemoteEventBus

        bus = EventBus(default_partitions=2)
        await bus.produce("swx1.tenant.t0.scored-events", {"n": 1},
                          key="a")
        consumer = bus.subscribe("swx1.tenant.t0.scored-events",
                                 group="t0.meter")
        stats = bus.stats()
        topic = stats["topics"]["swx1.tenant.t0.scored-events"]
        assert topic["partitions"] == 2 and topic["depth"] == 1
        assert stats["groups"]["t0.meter"]["members"] == 1
        assert stats["groups"]["t0.meter"]["lag"] == 1
        assert stats["fence_rejections"] == 0
        assert stats["members_evicted"] == 0
        # over the wire: same dict through the bus_stats op
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port)
        await remote.initialize()
        wired = await remote.bus_stats()
        assert wired["groups"]["t0.meter"]["lag"] == 1
        assert set(wired) == set(stats)
        consumer.close()
        await remote.stop()
        await server.stop()

    run(main())


# ---------------------------------------------------------------------------
# in-proc fleet harness with observability knobs
# ---------------------------------------------------------------------------


@contextlib.asynccontextmanager
async def observed_fleet(tmp_path, *, observe=True, n_workers=2,
                         n_tenants=2, megabatch=True, history=False,
                         worker_overrides=None):
    """The test_fleet in-proc topology (N fleet_managed runtimes + a
    driver hosting ingress/controller on ONE bus) with the observe
    levers parameterized: worker beats export onto the telemetry topic
    (fleet_managed → auto), the driver's controller hosts the
    FleetObserver, and `history=True` gives the driver a durable
    telemetry tier."""
    rp = dict(RP_SECTION)
    if megabatch:
        rp["megabatch"] = {"enabled": True}
    cfgs = [TenantConfig(tenant_id=f"t{i}",
                         sections={"rule-processing": rp})
            for i in range(n_tenants)]
    driver = ServiceRuntime(InstanceSettings(
        instance_id="fleet-test", fleet_interval_s=0.05,
        fleet_dead_after_s=1.5, rest_port=0, observe_enabled=observe,
        observe_interval_ms=50.0, trace_sample=1,
        observe_export_stages_every=2,
        data_dir=(str(tmp_path / "driver-data") if history else None)))
    driver.add_service(EventSourcesService(driver))
    controller = FleetController(
        driver, policy=AutoscalerPolicy(min_workers=n_workers,
                                        max_workers=n_workers))
    driver.add_child(controller)
    await driver.start()
    await _seed_registries(driver.bus, cfgs)
    runtimes, workers = {}, {}
    for i in range(n_workers):
        wid = f"w{i}"
        rt, worker = _worker_runtime(bus=driver.bus, wid=wid,
                                     data_dir=tmp_path,
                                     observe_enabled=observe,
                                     trace_sample=1,
                                     observe_export_stages_every=2,
                                     **(worker_overrides or {}))
        await rt.start()
        runtimes[wid] = rt
        workers[wid] = worker
    for cfg in cfgs:
        await driver.add_tenant(cfg)
    await wait_until(lambda: controller.snapshot()["converged"],
                     timeout=120.0)
    try:
        yield driver, controller, runtimes, workers, cfgs
    finally:
        for rt in runtimes.values():
            if rt.status.value != "stopped":
                await rt.stop()
        await driver.stop()


async def _score_rounds(driver, cfgs, rounds=3):
    """Submit `rounds` payloads per tenant; return per-tenant scored
    value arrays once everything came back."""
    consumers = {c.tenant_id: driver.bus.subscribe(
        driver.naming.tenant_topic(c.tenant_id, "scored-events"),
        group="observe-meter") for c in cfgs}
    scores = {c.tenant_id: [] for c in cfgs}
    sims = {c.tenant_id: DeviceSimulator(
        SimConfig(num_devices=DEVICES), tenant_id=c.tenant_id)
        for c in cfgs}
    for k in range(rounds):
        for tid, sim in sims.items():
            receiver = driver.api("event-sources").engine(tid) \
                .receiver("default")
            assert await receiver.submit(sim.payload(t=1000.0 + k)[0])

    def caught_up():
        for tid, consumer in consumers.items():
            for record in consumer.poll_nowait(max_records=128):
                scores[tid].append(np.asarray(record.value.score))
        return all(sum(len(s) for s in scores[t]) >= rounds * DEVICES
                   for t in scores)

    await wait_until(caught_up, timeout=90.0)
    for consumer in consumers.values():
        consumer.close()
    return {tid: np.sort(np.concatenate(arrs))
            for tid, arrs in scores.items()}


# ---------------------------------------------------------------------------
# telemetry export + fleet observer
# ---------------------------------------------------------------------------


def test_telemetry_export_and_fleet_observer(run, tmp_path):
    async def main():
        async with observed_fleet(tmp_path, history=True) as (
                driver, controller, runtimes, workers, cfgs):
            observer = controller.observer
            assert observer is driver.fleet_observer
            await _score_rounds(driver, cfgs)
            # both workers' beats folded (and the export counters move
            # worker-side)
            await wait_until(lambda: {"w0", "w1"} <= set(
                observer.workers), timeout=30.0)
            for rt in runtimes.values():
                assert rt.metrics.counter("observe.exports").value > 0
            # stage exports merge into ONE fleet critical path that
            # contains WORKER-side spine stages the driver never ran
            await wait_until(lambda: "rule-processing.score" in
                             observer.snapshot()["critical_path"]["stages"],
                             timeout=30.0)
            snap = observer.snapshot()
            stages = snap["critical_path"]["stages"]
            assert {"event-sources.receive", "event-sources.decode"} \
                <= set(stages)  # driver's own export joins the merge
            assert {"rule-processing.dispatch", "rule-processing.score",
                    "egress.publish"} <= set(stages)
            assert snap["critical_path"]["workers_merged"] >= 3
            # worker rows carry beat/liveness + mesh occupancy (the
            # megabatch pool reports per-device telemetry)
            w0 = snap["workers"]["w0"]
            assert w0["beats"] > 0 and w0["beat_age_s"] < 5.0
            meshes = [b for w in snap["workers"].values()
                      for b in w["mesh"]]
            assert meshes, "megabatch pools must report mesh stats"
            assert {"row_occupancy", "model_tflops_per_device",
                    "window_ms_live"} <= set(meshes[0])
            # lag matrix rows attribute tenants to their owners
            owners = controller.snapshot()["owners"]
            for tid, row in snap["lag_matrix"].items():
                if tid in owners:
                    assert row["worker"] == owners[tid]
            # broker stats ride along (the black-box closer)
            assert snap["broker"]["groups"], snap["broker"]
            assert "fence_rejections" in snap["broker"]
            # the driver's durable history holds the per-tenant lag
            # series the observer appends each tick
            assert ("t0", "lag") in driver.history.series()
            # fleet-merged prometheus exposition renders per-worker and
            # per-stage labeled gauges
            prom = observer.prometheus_text()
            assert 'swx_fleet_worker_loop_lag_ms{worker="w0"}' in prom
            assert 'stage="rule-processing.score"' in prom

    run(main())


def test_observer_rebuilds_from_topic_replay(run, tmp_path):
    """A restarted controller host (or a late-started observer) must
    rebuild every worker's last-known beat + stage export from the
    RETAINED telemetry stream — and keep tracking a worker across its
    own restart (fresh runtime, same id)."""
    async def main():
        from sitewhere_tpu.fleet.observer import FleetObserver

        async with observed_fleet(tmp_path) as (
                driver, controller, runtimes, workers, cfgs):
            observer = controller.observer
            await _score_rounds(driver, cfgs)
            await wait_until(lambda: {"w0", "w1"} <= set(
                observer.workers), timeout=30.0)
            # a SECOND observer starting late — beats already flowed —
            # rebuilds the same per-worker view from topic replay alone
            peer = ServiceRuntime(InstanceSettings(
                instance_id="fleet-test", observe_enabled=False),
                bus=driver.bus)
            late = FleetObserver(peer)
            peer.add_child(late)
            await peer.start()
            await wait_until(lambda: {"w0", "w1"} <= set(late.workers),
                             timeout=30.0)
            assert late.workers["w0"]["sample"] is not None
            await peer.stop()
            # worker restart: a FRESH runtime under the same id keeps
            # exporting; the observer's view refreshes (age resets,
            # beats keep arriving) instead of going stale
            rt0 = runtimes.pop("w0")
            workers.pop("w0")
            await rt0.stop()
            await asyncio.sleep(0.3)
            rt0b, w0b = _worker_runtime(bus=driver.bus, wid="w0",
                                        data_dir=tmp_path / "restart")
            await rt0b.start()
            runtimes["w0"] = rt0b
            workers["w0"] = w0b
            t_restart = time.monotonic()
            await wait_until(
                lambda: observer.workers.get("w0", {}).get(
                    "received_at", 0) > t_restart, timeout=30.0)
            assert observer.snapshot()["workers"]["w0"]["beat_age_s"] < 5.0

    run(main())


def test_fleet_observe_on_off_scored_equivalence(run, tmp_path):
    """The fleet observability plane is an observer: telemetry export,
    the FleetObserver, and history appends must not change a single
    scored output at the fleet level."""
    async def scores_with(observe, subdir):
        async with observed_fleet(tmp_path / subdir,
                                  observe=observe) as (
                driver, controller, runtimes, workers, cfgs):
            if observe:
                await wait_until(lambda: {"w0", "w1"} <= set(
                    controller.observer.workers), timeout=30.0)
            else:
                assert controller.observer is None
                for rt in runtimes.values():
                    assert rt.beat is None
            return await _score_rounds(driver, cfgs)

    async def main():
        on = await scores_with(True, "on")
        off = await scores_with(False, "off")
        assert set(on) == set(off)
        for tid in on:
            assert on[tid].shape == off[tid].shape
            np.testing.assert_allclose(on[tid], off[tid], rtol=1e-6)

    run(main())


# ---------------------------------------------------------------------------
# TRC01 wire-boundary trace-context contract
# ---------------------------------------------------------------------------


def test_trc01_wire_context_contract():
    from sitewhere_tpu.analysis.checkers_trace import (
        check_wire_trace_context,
    )
    from sitewhere_tpu.analysis.engine import lint_package, lint_sources

    # rebuilding a BatchContext at the wire boundary without trace_id
    # snaps the cross-process trace — flagged
    bad = ("def rewrap(self, value):\n"
           "    return BatchContext(tenant_id=value.ctx.tenant_id)\n")
    report = lint_sources({"sitewhere_tpu/kernel/wire.py": bad},
                          checkers=[check_wire_trace_context])
    assert [f.code for f in report.findings] == ["TRC01"]
    # threading the trace id through satisfies the contract
    good = ("def rewrap(self, value):\n"
            "    return BatchContext(tenant_id=value.ctx.tenant_id,\n"
            "                        trace_id=value.ctx.trace_id)\n")
    report = lint_sources({"sitewhere_tpu/kernel/wire.py": good},
                          checkers=[check_wire_trace_context])
    assert not report.findings
    # **kwargs may carry it (the codec's field-dict construction)
    splat = ("def rewrap(self, kwargs):\n"
             "    return BatchContext(**kwargs)\n")
    report = lint_sources({"sitewhere_tpu/kernel/codec.py": splat},
                          checkers=[check_wire_trace_context])
    assert not report.findings
    # modules OUTSIDE the wire boundary legitimately mint fresh
    # contexts (ingress edges start traces)
    report = lint_sources(
        {"sitewhere_tpu/services/event_sources.py": bad},
        checkers=[check_wire_trace_context])
    assert not report.findings
    # the live tree is clean (no baseline entries needed)
    package = lint_package()
    assert not [f for f in package.findings if f.code == "TRC01"]


# ---------------------------------------------------------------------------
# operator surfaces
# ---------------------------------------------------------------------------


def test_render_top_states_fleet_scope():
    report = {"critical_path": {"stages": {}, "sample": 64,
                                "span_count": 0},
              "beat": None,
              "fleet": {"epoch": 3, "workers": {
                  "w0": {"ready": True, "owned": ["t0"]},
                  "w1": {"ready": True, "owned": ["t1"]}}}}
    out = render_top(report)
    assert "LOCAL runtime only" in out
    assert "swx top --fleet" in out
    # a fleet-less runtime keeps the old screen (no scope noise)
    solo = render_top({"critical_path": {"stages": {}, "sample": 64,
                                         "span_count": 0}, "beat": None})
    assert "LOCAL runtime only" not in solo


def test_render_fleet_top():
    report = {
        "workers": {"w0": {
            "beat_age_s": 0.2, "seq": 9, "beats": 42,
            "loop_lag_ms": 1.5, "loop_lag_p99_ms": 3.0,
            "loop_stalls": 1, "consumer_lag_max": 17,
            "egress_backlog": 2, "scoring_pending": 5,
            "scoring_inflight": 1, "flow_modes": {"t0": "ok"},
            "mesh": [{"model": "zscore", "devices": 8,
                      "tenant_rows": 3, "row_capacity": 4,
                      "row_occupancy": 0.75, "window_ms_live": 1.5,
                      "model_tflops_per_device": 0.00123}]}},
        "critical_path": {"stages": {
            "wire.poll": {"kind": "queue", "count": 4, "p50_ms": 0.2,
                          "p95_ms": 0.8, "p99_ms": 1.0},
            "rule-processing.score": {"kind": "service", "count": 4,
                                      "p50_ms": 1.0, "p95_ms": 2.0,
                                      "p99_ms": 2.5}},
            "span_count": 8, "workers_merged": 2,
            "queue_wait_p99_ms": 1.0, "service_p99_ms": 2.5},
        "lag_matrix": {"t0": {"lag": 12, "worker": "w0"}},
        "mesh": {"w0": [{"model": "zscore", "devices": 8,
                         "tenant_rows": 3, "row_capacity": 4,
                         "row_occupancy": 0.75, "window_ms_live": 1.5,
                         "model_tflops_per_device": 0.00123}]},
        "telemetry": {"topic": "x.instance.telemetry", "records": 99,
                      "observer_lag": 0},
        "broker": {"topics": {"a": {}}, "groups": {
            "t0.inbound-processing": {"members": 1, "lag": 12,
                                      "generation": 1}},
            "fence_rejections": 1, "members_evicted": 2},
        "history": {"series": 3, "windows": 40, "segments": 1,
                    "window_s": 10.0},
    }
    out = render_fleet_top(report)
    assert "wire.poll" in out and "queue" in out
    assert "w0" in out and "42" in out
    assert "t0" in out and "12" in out
    assert "0.00123" in out
    assert "fence-rejections 1" in out
    assert "members-evicted 2" in out
    assert "history: 3 series" in out


# ---------------------------------------------------------------------------
# cross-worker trace continuity over REAL processes (the tier1 smoke)
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_cross_worker_trace_continuity(tmp_path):
    """A single injected device event produces ONE trace whose spine
    crosses ≥2 REAL OS processes over the wire bus: receive/decode on
    the ingress host, wire.poll/enrich/persist/dispatch/score/publish
    (+ the worker's own wire.produce hops) on its tenant's owner
    worker — ≥7 spine stages under one origin-scoped trace id, stitched
    via the worker ApiServer `trace` op and visible in the
    FleetObserver's merged fleet critical path. Run by scripts/tier1.sh
    as the fleet-observe smoke (marked slow: two jax-bearing worker
    processes)."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache_dir = os.path.join(repo, ".jax_cache")

    async def main():
        from sitewhere_tpu.kernel.bus import EventBus
        from sitewhere_tpu.kernel.wire import ApiChannel, BusServer

        n_workers = 2
        tenant_ids = [f"t{i}" for i in range(4)]
        bus = EventBus(default_partitions=4, retention=65536)
        driver = ServiceRuntime(InstanceSettings(
            instance_id="fleet-obs", bus_retention=65536,
            trace_sample=1, observe_interval_ms=100.0,
            fleet_interval_s=0.25, fleet_dead_after_s=8.0,
            flow_degrade_at=10.0, flow_defer_at=10.0), bus=bus)
        driver.add_service(EventSourcesService(driver))
        controller = FleetController(
            driver, policy=AutoscalerPolicy(min_workers=n_workers,
                                            max_workers=n_workers,
                                            scale_up_lag=1e18,
                                            imbalance_ratio=1e18))
        driver.add_child(controller)
        cfgs = [TenantConfig(tenant_id=tid, sections={
            "rule-processing": dict(RP_SECTION)}) for tid in tenant_ids]
        await driver.start()
        await _seed_registries(bus, cfgs, instance_id="fleet-obs")
        broker = BusServer(bus)
        await broker.start()

        procs = {}
        api_ports = {}
        try:
            for i in range(n_workers):
                wid = f"w{i}"
                api_ports[wid] = _free_port()
                cfg = {
                    "worker_id": wid, "host": "127.0.0.1",
                    "port": broker.port, "instance_id": "fleet-obs",
                    "force_cpu": True, "jax_cache": cache_dir,
                    "api_port": api_ports[wid], "log_level": "WARNING",
                    "settings": {
                        "trace_sample": 1,
                        "observe_interval_ms": 100.0,
                        "observe_export_stages_every": 2,
                        "fleet_heartbeat_s": 0.25,
                        "flow_degrade_at": 10.0, "flow_defer_at": 10.0,
                        "data_dir": str(tmp_path / wid),
                    },
                }
                env = dict(os.environ)
                env["JAX_PLATFORMS"] = "cpu"
                env["PYTHONPATH"] = repo + os.pathsep \
                    + env.get("PYTHONPATH", "")
                import json as _json
                procs[wid] = subprocess.Popen(
                    [sys.executable, "-m",
                     "sitewhere_tpu.fleet.worker_main",
                     _json.dumps(cfg)],
                    stdout=subprocess.DEVNULL, env=env, cwd=repo)
            for cfg in cfgs:
                await driver.add_tenant(cfg)
            t0 = time.monotonic()
            while True:
                snap = controller.snapshot()
                if snap["converged"] and len(snap["workers"]) \
                        >= n_workers:
                    break
                dead = [w for w, p in procs.items()
                        if p.poll() is not None]
                assert not dead, f"worker(s) died during startup: {dead}"
                assert time.monotonic() - t0 < 180.0, \
                    f"fleet did not converge: {snap['workers']}"
                await asyncio.sleep(0.25)
            owners = controller.snapshot()["owners"]
            assert len(set(owners.values())) >= 2, (
                f"placement put every tenant on one worker: {owners}")

            # one scored round per tenant, metered off the shared bus
            meters = {tid: bus.subscribe(
                driver.naming.tenant_topic(tid, "scored-events"),
                group="trace-meter") for tid in tenant_ids}
            sims = {tid: DeviceSimulator(
                SimConfig(num_devices=DEVICES), tenant_id=tid)
                for tid in tenant_ids}
            scored = {tid: 0 for tid in tenant_ids}
            for tid in tenant_ids:
                receiver = driver.api("event-sources").engine(tid) \
                    .receiver("default")
                assert await receiver.submit(
                    sims[tid].payload(t=1000.0)[0])

            def caught_up():
                for tid, consumer in meters.items():
                    for record in consumer.poll_nowait(max_records=64):
                        scored[tid] += len(record.value)
                return all(scored[t] >= DEVICES for t in tenant_ids)

            await wait_until(caught_up, timeout=120.0)

            # ONE trace id from the ingress host's receive span …
            victim = tenant_ids[0]
            owner = owners[victim]
            receive = [s for s in driver.tracer.spans(
                stage="event-sources.receive", tenant=victim, limit=-1)]
            assert receive, "ingress host recorded no receive span"
            trace_id = receive[-1].trace_id
            driver_spans = driver.tracer.trace(trace_id)

            # … stitched with the owner worker's spans via the wire
            # trace op (retry: the worker records spans as it settles)
            channel = ApiChannel("127.0.0.1", api_ports[owner])
            worker_spans = []
            deadline = time.monotonic() + 60.0
            want = {"rule-processing.score", "egress.publish"}
            while time.monotonic() < deadline:
                worker_spans = await channel.trace(trace_id)
                if want <= {s["stage"] for s in worker_spans}:
                    break
                await asyncio.sleep(0.5)
            channel.close()

            driver_stages = {s.stage for s in driver_spans}
            worker_stages = {s["stage"] for s in worker_spans}
            assert {"event-sources.receive",
                    "event-sources.decode"} <= driver_stages
            # the broker hop is no longer dark: the worker polled the
            # record over the wire and produced its downstream hops
            # over the wire
            assert "wire.poll" in worker_stages, worker_stages
            assert "wire.produce" in worker_stages, worker_stages
            assert {"inbound.enrich", "event-management.persist",
                    "rule-processing.dispatch", "rule-processing.score",
                    "egress.publish"} <= worker_stages, worker_stages
            spine = driver_stages | worker_stages
            assert len(spine & {
                "event-sources.receive", "event-sources.decode",
                "wire.poll", "wire.produce", "inbound.enrich",
                "event-management.persist", "rule-processing.dispatch",
                "rule-processing.score", "egress.publish"}) >= 7
            # every stitched span carries the ONE origin-scoped id
            assert all(s.trace_id == trace_id for s in driver_spans)
            assert all(s["trace_id"] == trace_id for s in worker_spans)

            # and the fleet observer's merged critical path covers the
            # worker-side stages the driver never ran (the
            # `swx top --fleet` data source)
            observer = controller.observer
            await wait_until(
                lambda: "rule-processing.score" in
                observer.snapshot()["critical_path"]["stages"],
                timeout=60.0)
            merged = observer.snapshot()["critical_path"]["stages"]
            assert "wire.poll" in merged
            for consumer in meters.values():
                consumer.close()
        finally:
            for proc in procs.values():
                if proc.poll() is None:
                    proc.terminate()
            for proc in procs.values():
                try:
                    proc.wait(timeout=20.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
            await broker.stop()
            await driver.stop()

    asyncio.run(main())
