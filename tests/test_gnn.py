"""Config 5 [BASELINE.json]: GNN predictive maintenance over the
device-asset graph — graph builder, model numerics, risk propagation
through shared assets, mesh-sharded equivalence, and the e2e
batch-operation sweep."""

import numpy as np
import jax

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.batch import AlertBatch, BatchContext
from sitewhere_tpu.domain.model import (
    Area,
    Asset,
    Device,
    DeviceAssignment,
    DeviceType,
)
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.models.gnn import GnnConfig, GnnMaintenanceModel
from sitewhere_tpu.models.graph import (
    FEATURE_DIM,
    NODE_AREA,
    NODE_ASSET,
    NODE_DEVICE,
    build_fleet_graph,
)
from sitewhere_tpu.parallel.mesh import make_mesh
from sitewhere_tpu.persistence.memory import InMemoryDeviceManagement
from sitewhere_tpu.persistence.telemetry import TelemetryStore
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig
from sitewhere_tpu.training.maintenance import (
    MaintenanceTrainer,
    MaintenanceTrainerConfig,
    build_maintenance_model,
)

from tests.test_pipeline import wait_until


def _fixture_fleet(n_devices=12, n_assets=3, n_areas=2):
    """Small fleet: devices round-robin across assets; assets' devices
    grouped into areas; one parent area."""
    dm = InMemoryDeviceManagement()
    dt = DeviceType(token="pump", name="Pump")
    dm.create_device_type(dt)
    # assets live in asset-management [SURVEY.md §2.2]; the graph builder
    # only needs their ids via assignments, so bare entities suffice here
    assets = [Asset(token=f"asset-{i}", name=f"A{i}") for i in range(n_assets)]
    parent = Area(token="site", name="Site")
    areas = [parent] + [Area(token=f"area-{i}", name=f"Z{i}",
                             parent_area_id=parent.id)
                        for i in range(n_areas)]
    for ar in areas:
        dm.create_area(ar)
    devices = []
    for i in range(n_devices):
        d = dm.create_device(Device(token=f"p-{i}", device_type_id=dt.id))
        asset = assets[i % n_assets]
        area = areas[1 + (i % n_assets) % n_areas]
        dm.create_device_assignment(DeviceAssignment(
            device_id=d.id, asset_id=asset.id, area_id=area.id,
            token=f"p-{i}-a"))
        devices.append(d)
    return dm, devices, assets, areas


def _warm_store(n_devices, ticks=40, drift_fraction=0.0, seed=5,
                drift_per_hour=8.0):
    store = TelemetryStore(history=64)
    sim = DeviceSimulator(SimConfig(
        num_devices=n_devices, seed=seed, drift_fraction=drift_fraction,
        drift_per_hour=drift_per_hour if drift_fraction else 0.0),
        tenant_id="t")
    for k in range(ticks):
        batch, _ = sim.tick(t=60.0 * k)
        store.append_measurements(batch)
    return store, sim


def test_graph_builder_topology_and_features():
    dm, devices, assets, areas = _fixture_fleet(12, 3, 2)
    store, _ = _warm_store(12)
    g = build_fleet_graph(dm, store, window=32, max_degree=8)

    assert g.n_devices == 12
    assert g.n_real == 12 + 3 + 3          # devices + assets + 3 areas
    assert g.n_pad >= g.n_real and g.n_pad % 8 == 0
    assert g.node_feat.shape == (g.n_pad, FEATURE_DIM)
    # every device node: asset edge + area edge
    assert (g.nbr_mask[:12].sum(1) == 2).all()
    assert (g.node_type[:12] == NODE_DEVICE).all()
    assert (g.node_type[12:15] == NODE_ASSET).all()
    assert (g.node_type[15:18] == NODE_AREA).all()
    # asset degree: 4 devices each (12 / 3)
    assert (g.nbr_mask[12:15].sum(1) == 4).all()
    # undirected symmetry: device 0's asset neighbor lists device 0 back
    a0 = g.neighbors[0, 0]
    assert 0 in g.neighbors[a0][g.nbr_mask[a0]]
    # padding rows are inert
    assert not g.nbr_mask[g.n_real:].any()
    assert (g.node_feat[g.n_real:] == 0).all()
    # labels cover device nodes only
    assert g.label_mask[:12].all() and not g.label_mask[12:].any()


def test_graph_features_pick_up_drift():
    store, sim = _warm_store(32, ticks=50, drift_fraction=0.3)
    from sitewhere_tpu.models.graph import device_features

    feats = device_features(store, 32, window=48)
    slopes = feats[:, 3]
    # signed means: the sine's local slopes average out across random
    # phases, the degradation drift does not
    assert slopes[sim.drifting].mean() > slopes[~sim.drifting].mean() + 2.0


def test_gnn_loss_decreases_and_risk_orders():
    """Train on a fleet where one asset's devices fail; the unlabeled
    device sharing that asset must score above devices on healthy
    assets (risk propagation through the graph)."""
    dm, devices, assets, areas = _fixture_fleet(12, 3, 2)
    store, _ = _warm_store(12)
    # devices 0,3,6 are on asset 0; 9 also on asset 0 but unlabeled
    failed = np.asarray([0, 3, 6])
    g = build_fleet_graph(dm, store, window=32, max_degree=8,
                          failed_device_indices=failed)
    # neutralize telemetry features: only graph structure should matter
    g.node_feat[:12, :5] = 0.0

    model = build_maintenance_model(hidden=16, layers=2, max_degree=8)
    trainer = MaintenanceTrainer(model, MaintenanceTrainerConfig(
        learning_rate=3e-2, steps=150, seed=1))
    params, report = trainer.train(g)
    assert report["losses"][-1] < report["losses"][0]

    risk = trainer.score(params, g)
    on_failed_asset = 9          # shares asset 0 with the failed devices
    healthy = [1, 2, 4, 5, 7, 8, 10, 11]  # devices on assets 1 and 2
    assert risk[on_failed_asset] > max(risk[d] for d in healthy)


def test_gnn_sharded_inference_matches_single_device():
    dm, *_ = _fixture_fleet(24, 4, 2)
    store, _ = _warm_store(24)
    g = build_fleet_graph(dm, store, window=32, max_degree=8)
    model = build_maintenance_model(hidden=16, layers=2, max_degree=8)
    params = model.init(jax.random.PRNGKey(0))

    plain = MaintenanceTrainer(model)
    sharded = MaintenanceTrainer(model, mesh=make_mesh(data=8, model=1))
    r1 = plain.score(params, g)
    r2 = sharded.score(params, g)
    np.testing.assert_allclose(r1, r2, rtol=2e-4, atol=1e-5)


def test_e2e_maintenance_sweep_batch_operation(run):
    """Full config-5 slice in the service runtime: alert history labels →
    graph → GNN sweep → maintenance alerts + checkpoint."""
    import tempfile

    from sitewhere_tpu.services import (
        BatchOperationsService,
        DeviceManagementService,
        DeviceStateService,
        EventManagementService,
        EventSourcesService,
        InboundProcessingService,
    )

    async def main():
        with tempfile.TemporaryDirectory() as ckpt_root:
            rt = ServiceRuntime(InstanceSettings(instance_id="maint"))
            for cls in (DeviceManagementService, EventSourcesService,
                        InboundProcessingService, EventManagementService,
                        DeviceStateService, BatchOperationsService):
                rt.add_service(cls(rt))
            await rt.start()
            await rt.add_tenant(TenantConfig(
                tenant_id="acme",
                sections={"batch-operations": {"checkpoint_root": ckpt_root},
                          "event-management": {"history": 64}}))
            dm = rt.api("device-management").management("acme")
            dt = DeviceType(token="pump", name="Pump")
            # 3 assets × 8 devices
            dm.spi.create_device_type(dt)
            assets = [Asset(token=f"as-{i}", name=f"A{i}") for i in range(3)]
            for i in range(24):
                d = dm.spi.create_device(Device(
                    token=f"p-{i}", device_type_id=dt.id))
                dm.spi.create_device_assignment(DeviceAssignment(
                    device_id=d.id, asset_id=assets[i % 3].id,
                    token=f"p-{i}-a"))

            em = rt.api("event-management").management("acme")
            # asset-0's devices degrade (drift) — the telemetry signal
            # that accompanies the incident history
            sim = DeviceSimulator(SimConfig(num_devices=24, seed=9,
                                            drift_per_hour=6.0),
                                  tenant_id="acme")
            sim.drifting = np.arange(24) % 3 == 0
            for k in range(40):
                batch, _ = sim.tick(t=60.0 * k)
                em.telemetry.append_measurements(batch)

            # incident history: 5 of the 8 asset-0 devices have failed;
            # 15, 18, 21 are the unlabeled siblings the sweep must flag
            failed = np.asarray([0, 3, 6, 9, 12], np.uint32)
            em.add_alert_batch(AlertBatch(
                ctx=BatchContext(tenant_id="acme", source="test"),
                device_index=failed,
                level=np.full(failed.shape[0], 2, np.uint8),
                type=["hardware.failure"] * failed.shape[0],
                message=["failed"] * failed.shape[0],
                ts=np.full(failed.shape[0], 2400.0), source="device"))

            ops = rt.api("batch-operations").operations("acme")
            # De-flaked (the documented full-suite-only intermittent,
            # known since PR 6): at lr=3e-2/steps=200 the weakest
            # unlabeled sibling's risk sat AT the 0.5 threshold, and the
            # chaotic training trajectory amplified XLA-CPU reduction-
            # order noise (thread-load dependent) across the boundary.
            # lr=1e-2/steps=300/threshold=0.3 was chosen by a
            # perturbation probe (±1e-4 feature noise, 16 trials):
            # sibling risk min 0.544, healthy-asset max 0.001 — margins
            # on BOTH sides of the threshold instead of a knife edge.
            op = await ops.submit_maintenance_operation(
                hidden=16, layers=2, max_degree=8, steps=300,
                learning_rate=1e-2, window=32, risk_threshold=0.3,
                feature_dropout=0.5)
            done = await ops.wait_for_operation(op.id, timeout=120.0)
            result = done.parameters["result"]
            assert result["devices"] == 24
            assert result["labeled_failures"] == 5
            assert result["edges"] == 24
            assert result["checkpoint_version"] == 1
            # asset-0's unlabeled siblings predicted at risk → new alerts
            maint = [a for a in em.list_alerts(limit=10_000)
                     if a.type == "maintenance.risk"]
            assert result["devices_at_risk"] == len(maint)
            at_risk_idx = {dm.get_device(a.device_id).index for a in maint}
            # the unlabeled asset-0 siblings are flagged...
            assert {15, 18, 21} <= at_risk_idx, at_risk_idx
            # ...no already-failed device is re-alerted, and no device on
            # a healthy asset is dragged in
            assert not (at_risk_idx & set(failed.tolist()))
            assert all(i % 3 == 0 for i in at_risk_idx), at_risk_idx

            # second sweep: the first sweep's own maintenance.risk alerts
            # must NOT become training labels (self-reinforcement loop)
            op2 = await ops.submit_maintenance_operation(
                hidden=16, layers=2, max_degree=8, steps=50,
                learning_rate=1e-2, window=32, risk_threshold=0.3,
                feature_dropout=0.5)
            done2 = await ops.wait_for_operation(op2.id, timeout=120.0)
            assert done2.parameters["result"]["labeled_failures"] == 5
            assert done2.parameters["result"]["checkpoint_version"] == 2
            await rt.stop()

    run(main())
