"""Predictive control plane (fleet/forecast.py): history→feature-window
edge cases, tenant-0 serving through the shared pool, and the planner's
confidence gate / forecast-attributed decisions."""

import asyncio
import math
import time
from types import SimpleNamespace

import numpy as np
import pytest

from sitewhere_tpu.config import RESERVED_TENANT, InstanceSettings
from sitewhere_tpu.fleet.controller import AutoscalerPolicy
from sitewhere_tpu.fleet.forecast import (
    LOAD_SIGNALS,
    FeaturePipeline,
    PredictivePlanner,
)
from sitewhere_tpu.kernel.metrics import MetricsRegistry
from sitewhere_tpu.kernel.observe import per_tenant_lags
from sitewhere_tpu.persistence.durable import TelemetryHistory

WS = 1.0


def make_history(tmp_path, name="hist", window_s=WS):
    return TelemetryHistory(str(tmp_path / name), window_s=window_s)


def fill_ramp(h, tenant, t0, n, *, slope=100.0, gap=()):
    """n windows of a lag ramp, skipping the window indices in `gap`
    (a worker restart: the beat simply wrote nothing)."""
    for i in range(n):
        if i in gap:
            continue
        h.append(tenant, "lag", slope * i, t=t0 + i * WS + 0.25)
        h.append(tenant, "lag", slope * i, t=t0 + i * WS + 0.75)


# -- feature pipeline edge cases ---------------------------------------------


def test_restart_gap_windows_are_invalid_not_zero(tmp_path):
    h = make_history(tmp_path)
    t0 = math.floor(time.time() / WS) * WS - 40 * WS
    fill_ramp(h, "acme", t0, 20, gap=(7, 8))
    h.flush()
    fp = FeaturePipeline(h)
    vals, valid, starts = fp.load_series("acme", window=20,
                                         until=t0 + 20 * WS)
    assert starts[0] == t0
    assert not valid[7] and not valid[8]
    assert valid[6] and valid[9]
    # the gap must be masked, not silently zero-valued "load vanished"
    assert vals[9] == pytest.approx(900.0)
    # and the gap mask rides into the training windows
    w, wv = fp.training_windows(["acme"], 12, until=t0 + 20 * WS)
    assert w.shape[0] > 0
    assert (~wv).any()


def test_open_live_tail_window_is_readable(tmp_path):
    h = make_history(tmp_path)
    open_w = math.floor(time.time() / WS) * WS
    t0 = open_w - 5 * WS
    fill_ramp(h, "acme", t0, 5)
    # the OPEN window: appended, never flushed — the live tail must
    # still resolve onto the grid when `until` reaches past it
    h.append("acme", "lag", 999.0, t=open_w + 0.1)
    fp = FeaturePipeline(h)
    vals, valid, starts = fp.load_series("acme", window=6,
                                         until=open_w + WS)
    assert starts[-1] == open_w
    assert valid[-1] and vals[-1] == pytest.approx(999.0)
    # serving-grid semantics: until at the open window START excludes it
    vals2, valid2, starts2 = fp.load_series("acme", window=5, until=open_w)
    assert starts2[-1] == open_w - WS


def test_flush_split_rows_merge_to_one_window_mean(tmp_path):
    h = make_history(tmp_path)
    w0 = math.floor(time.time() / WS) * WS - 10 * WS
    h.append("acme", "lag", 100.0, t=w0 + 0.2)
    h.flush()  # closes the open window: the next append SPLITS the row
    h.append("acme", "lag", 300.0, t=w0 + 0.8)
    h.flush()
    fp = FeaturePipeline(h)
    vals, valid, starts = fp.load_series("acme", window=1, until=w0 + WS)
    assert valid[0]
    # merged at read: mean over BOTH rows' points, not either alone
    assert vals[0] == pytest.approx(200.0)


def test_since_until_boundary_semantics_on_grid(tmp_path):
    h = make_history(tmp_path)
    w0 = math.floor(time.time() / WS) * WS - 20 * WS
    fill_ramp(h, "acme", w0, 10)
    h.flush()
    fp = FeaturePipeline(h)
    # until is EXCLUSIVE on window start: a grid ending at until=w0+5
    # must not contain the window starting at w0+5
    vals, valid, starts = fp.load_series("acme", window=5, until=w0 + 5 * WS)
    assert starts[0] == w0 and starts[-1] == w0 + 4 * WS
    assert valid.all()
    assert vals[-1] == pytest.approx(400.0)
    # and exactly n windows come back for an n-window span
    x, v, s = fp.features(["acme"], window=10, until=w0 + 10 * WS)
    assert x.shape == (1, 10, len(fp.signals))
    li = fp.signals.index("lag")
    assert v[0, :, li].all()


def test_restart_survival_feeds_feature_builder(tmp_path):
    """History written before a 'restart' (new TelemetryHistory over the
    same directory) must still resolve on the same grid afterwards."""
    t0 = math.floor(time.time() / WS) * WS - 30 * WS
    h = make_history(tmp_path, "h")
    fill_ramp(h, "acme", t0, 10)
    h.close()  # process death; closed rows are on disk
    h2 = TelemetryHistory(str(tmp_path / "h"), window_s=WS)
    fill_ramp(h2, "acme", t0 + 14 * WS, 6, slope=50.0)
    h2.flush()
    fp = FeaturePipeline(h2)
    vals, valid, starts = fp.load_series("acme", window=20,
                                         until=t0 + 20 * WS)
    assert valid[:10].all()          # pre-restart windows replayed
    assert not valid[10:14].any()    # the downtime hole stays a hole
    assert valid[14:].all()
    h2.close()


# -- reserved tenant-0 roster rules ------------------------------------------


def test_per_tenant_lags_drops_reserved_tenant():
    lags = {
        "acme.inbound": {"t": 5},
        f"{RESERVED_TENANT}.inbound": {"t": 50},
        "fleet.controller": {"t": 9},
    }
    out = per_tenant_lags(lags)
    assert out == {"acme": 5}


def test_admit_fair_bypasses_reserved_tenant(run):
    from sitewhere_tpu.kernel.flow import FlowController

    settings = InstanceSettings(flow_inbound_rate=1.0)
    flow = FlowController(settings=settings, metrics=MetricsRegistry())

    async def main():
        # the shared budget is 1 ev/s: a customer admit would queue,
        # the platform's own slot must not
        t0 = time.monotonic()
        for _ in range(20):
            await flow.admit_fair(RESERVED_TENANT, cost=5.0)
        assert time.monotonic() - t0 < 0.5

    run(main())


def test_add_tenant_rejects_reserved_id(run):
    from sitewhere_tpu.config import TenantConfig
    from sitewhere_tpu.kernel.service import ServiceRuntime

    async def main():
        runtime = ServiceRuntime(InstanceSettings(
            instance_id="test", observe_enabled=False))
        await runtime.start()
        try:
            with pytest.raises(ValueError, match="reserved"):
                await runtime.add_tenant(
                    TenantConfig(tenant_id=RESERVED_TENANT))
        finally:
            await runtime.stop()

    run(main())


# -- the planner -------------------------------------------------------------


def make_controller(tmp_path, history, **policy_kw):
    settings = InstanceSettings(
        data_dir=str(tmp_path / "data"),
        fleet_forecast_window=16,
        fleet_forecast_horizon_s=4.0,
        fleet_forecast_interval_s=0.0,
        fleet_forecast_min_windows=6,
        fleet_forecast_max_stale_s=30.0,
    )
    runtime = SimpleNamespace(settings=settings, metrics=MetricsRegistry(),
                              history=history, tracer=None, faults=None)
    policy = AutoscalerPolicy(**{"scale_up_lag": 300.0, "cooldown_s": 0.0,
                                 **policy_kw})
    return SimpleNamespace(runtime=runtime, policy=policy,
                           tenants={"acme": object(), "beta": object()},
                           _last_scale_t=-1e9, _pending_spawns=0)


def test_cold_start_demotes_to_reactive(tmp_path):
    h = make_history(tmp_path)
    t0 = math.floor(time.time() / WS) * WS - 30 * WS
    fill_ramp(h, "acme", t0, 20)
    h.flush()
    c = make_controller(tmp_path, h)
    planner = PredictivePlanner(c)
    # cold: serving never started, nothing trained → pure-reactive,
    # demotion counted ONCE (transition), not once per gated tick
    assert planner.decide({"w1": 0.0}, {}) is None
    assert planner.decide({"w1": 0.0}, {}) is None
    assert planner.demotions_c.value == 1
    assert "not started" in planner.snapshot()["gate"]
    h.close()


def test_trains_from_history_and_emits_forecast_decision(tmp_path):
    """The tier-1 story end to end: synthetic ramp history → trainer →
    tenant-0 slot through the shared pool → one forecast-attributed
    add_replica out of decide()."""
    h = make_history(tmp_path)
    now_w = math.floor(time.time() / WS) * WS
    t0 = now_w - 60 * WS
    for tid in ("acme", "beta"):
        fill_ramp(h, tid, t0, 58, slope=40.0)
    h.flush()
    c = make_controller(tmp_path, h)
    planner = PredictivePlanner(c)
    report = planner.train_from_history(steps=25)
    assert report is not None and report["version"] >= 1
    assert planner.trainings_c.value == 1

    async def run():
        await planner.tick()   # starts serving, backfills, registers
        deadline = time.monotonic() + 30.0
        while not planner.forecasts and time.monotonic() < deadline:
            # keep the ramp alive so newly CLOSED windows keep arriving
            wall = time.time()
            i = (wall - t0) / WS
            for tid in ("acme", "beta"):
                h.append(tid, "lag", 40.0 * i, t=wall)
            await planner.tick()
            await asyncio.sleep(0.25)
        return planner.decide({"w1": 1.0}, {})

    decision = asyncio.run(run())
    try:
        assert planner.forecasts, "no forecast settled through the pool"
        assert decision is not None, planner.snapshot()
        assert decision["action"] == "add_replica"
        assert decision["reason"].startswith("forecast:")
        prov = decision["forecast"]
        assert prov["horizon_s"] == pytest.approx(4.0)
        assert prov["predicted_load"] > 0
        assert planner.decisions_c.value == 1
        # the pool path really served it: tenant-0 is a registered slot
        assert RESERVED_TENANT in planner.pool.tenants
        assert planner.snapshot()["gate"] == "ok"
    finally:
        planner.close()
        h.close()


def test_stale_forecast_regates(tmp_path):
    h = make_history(tmp_path)
    t0 = math.floor(time.time() / WS) * WS - 30 * WS
    fill_ramp(h, "acme", t0, 28)
    h.flush()
    c = make_controller(tmp_path, h)
    planner = PredictivePlanner(c)
    planner._trained = True
    planner.pool = object()  # serving "up" for the gate's purposes
    planner.slot = object()
    planner.forecasts["acme"] = {
        "load": 1e6, "made_t": time.time() - 100,
        "made_monotonic": time.monotonic() - 100.0, "model_version": 1}
    assert planner.decide({"w1": 0.0}, {}) is None
    assert "no fresh forecast" in planner.snapshot()["gate"]
    # freshen it: the same forecast now drives a decision
    planner.forecasts["acme"]["made_monotonic"] = time.monotonic()
    d = planner.decide({"w1": 0.0}, {})
    assert d is not None and "forecast" in d
    h.close()


def test_high_horizon_error_demotes(tmp_path):
    h = make_history(tmp_path)
    t0 = math.floor(time.time() / WS) * WS - 30 * WS
    fill_ramp(h, "acme", t0, 28)
    h.flush()
    c = make_controller(tmp_path, h)
    planner = PredictivePlanner(c)
    planner._trained = True
    planner.pool = object()
    planner.slot = object()
    planner.forecasts["acme"] = {
        "load": 1e6, "made_t": time.time(),
        "made_monotonic": time.monotonic(), "model_version": 1}
    planner.error_ema = planner.error_gate * 2
    assert planner.decide({"w1": 0.0}, {}) is None
    assert "horizon error" in planner.snapshot()["gate"]
    assert planner.demotions_c.value == 1
    h.close()
