"""Robustness subsystem: supervised service loops (restart budget),
poison-record quarantine (per-tenant DLQ with provenance + replay), and
the deterministic FaultInjector — including the chaos integration test
(faults at bus poll, durable flush, and scoring dispatch) proving the
pipeline keeps draining and stops cleanly."""

import asyncio

import numpy as np
import pytest

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.kernel.bus import TopicNaming
from sitewhere_tpu.kernel.dlq import list_dead_letters, replay_dead_letters
from sitewhere_tpu.kernel.faults import FaultInjected, FaultInjector
from sitewhere_tpu.kernel.lifecycle import (
    BackgroundTaskComponent,
    LifecycleStatus,
    SupervisorPolicy,
)
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.services import (
    DeviceManagementService,
    DeviceStateService,
    EventManagementService,
    InboundProcessingService,
    RuleProcessingService,
)

from tests.test_pipeline import wait_until


# -- supervision ------------------------------------------------------------

class Crashy(BackgroundTaskComponent):
    """Loop that crashes its first `crashes` runs, then parks forever."""

    def __init__(self, crashes: int, policy: SupervisorPolicy):
        super().__init__("crashy", supervisor=policy)
        self.crashes = crashes
        self.runs = 0

    async def _run(self):
        self.runs += 1
        if self.runs <= self.crashes:
            raise RuntimeError(f"boom {self.runs}")
        await asyncio.Event().wait()  # healthy: run until cancelled


def test_supervisor_restarts_within_budget(run):
    async def main():
        c = Crashy(3, SupervisorPolicy(max_restarts=5, window_s=60.0,
                                       base_backoff_s=0.005))
        await c.start()
        await wait_until(lambda: c.runs == 4, timeout=5.0)
        assert c.status is LifecycleStatus.STARTED
        assert c.restart_count == 3
        assert c.error is None
        tree = c.state_tree()
        assert tree["restarts"] == 3
        assert "boom 3" in tree["last_crash"]
        await c.stop()
        assert c.status is LifecycleStatus.STOPPED

    run(main())


def test_supervisor_budget_exhausted_goes_error(run):
    async def main():
        c = Crashy(100, SupervisorPolicy(max_restarts=2, window_s=60.0,
                                         base_backoff_s=0.005))
        await c.start()
        await wait_until(
            lambda: c.status is LifecycleStatus.LIFECYCLE_ERROR, timeout=5.0)
        # 1 initial run + 2 restarts; the 3rd crash is over budget
        assert c.runs == 3
        assert c.restart_count == 2
        assert "boom 3" in repr(c.error)
        assert c.state_tree()["status"] == "lifecycle_error"
        await c.stop()  # an errored component still stops cleanly

    run(main())


def test_supervisor_disabled_is_fatal_first_crash(run):
    async def main():
        c = Crashy(1, SupervisorPolicy(max_restarts=0))
        await c.start()
        await wait_until(
            lambda: c.status is LifecycleStatus.LIFECYCLE_ERROR, timeout=5.0)
        assert c.runs == 1 and c.restart_count == 0
        await c.stop()

    run(main())


def test_stop_during_backoff_cancels_restart(run):
    async def main():
        c = Crashy(5, SupervisorPolicy(max_restarts=5,
                                       base_backoff_s=30.0))
        await c.start()
        await wait_until(lambda: c.runs == 1 and c._restart_task is not None,
                         timeout=5.0)
        await c.stop()  # must not wait out the 30 s backoff
        assert c.status is LifecycleStatus.STOPPED
        await asyncio.sleep(0.05)
        assert c.runs == 1  # no zombie respawn after stop

    run(main())


# -- fault injector ---------------------------------------------------------

def test_fault_injector_deterministic_per_site():
    a = FaultInjector(seed=7).arm("s1", rate=0.3).arm("s2", rate=0.3)
    b = FaultInjector(seed=7).arm("s1", rate=0.3)
    # interleave a's sites; b draws s1 alone — same s1 sequence either way
    seq_a = [(a.decide("s1"), a.decide("s2")) for _ in range(200)]
    seq_b = [b.decide("s1") for _ in range(200)]
    assert [x for x, _ in seq_a] == seq_b
    # a different seed produces a different sequence
    c = FaultInjector(seed=8).arm("s1", rate=0.3)
    assert [c.decide("s1") for _ in range(200)] != seq_b
    assert a.snapshot()["s1"]["decided"] == 200
    assert a.snapshot()["s1"]["injected"] == seq_b.count("raise")


def test_fault_injector_caps_and_modes():
    fi = FaultInjector(seed=0).arm("x", rate=1.0, max_faults=2)
    with pytest.raises(FaultInjected):
        fi.check("x")
    with pytest.raises(FaultInjected):
        fi.check("x")
    fi.check("x")  # cap reached: no more faults
    assert fi.total_injected == 2
    fi.enabled = False
    assert fi.decide("x") == "ok"
    # unarmed site is always ok
    assert FaultInjector().decide("never-armed") == "ok"


# -- DLQ quarantine + replay ------------------------------------------------

def _measurements(n: int, t: float, tenant="acme") -> MeasurementBatch:
    return MeasurementBatch(
        BatchContext(tenant_id=tenant, source="test"),
        np.arange(n, dtype=np.uint32), np.zeros(n, np.uint16),
        np.random.default_rng(int(t)).normal(20.0, 2.0, n).astype(np.float32),
        np.full(n, t))


async def _mini_runtime(tmp_path=None, rule=False):
    sections = {}
    if rule:
        sections["rule-processing"] = {
            "model": "zscore", "model_config": {"window": 8},
            "batch_window_ms": 1.0, "buckets": [64]}
    if tmp_path is not None:
        sections["event-management"] = {"data_dir": str(tmp_path)}
    rt = ServiceRuntime(InstanceSettings(
        instance_id="robust",
        # fast restarts so chaos recovery fits in test timeouts
        supervisor_base_backoff_s=0.005, supervisor_max_backoff_s=0.1))
    rt.add_service(DeviceManagementService(rt))
    rt.add_service(InboundProcessingService(rt))
    rt.add_service(EventManagementService(rt))
    rt.add_service(DeviceStateService(rt))
    if rule:
        rt.add_service(RuleProcessingService(rt))
    fi = rt.install_faults(FaultInjector(seed=42))
    await rt.start()
    await rt.add_tenant(TenantConfig(tenant_id="acme", sections=sections))
    dm = rt.api("device-management").management("acme")
    dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 32)
    return rt, fi


def test_dlq_publish_and_replay_roundtrip(run):
    async def main():
        rt, fi = await _mini_runtime()
        try:
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            dlq = rt.naming.tenant_topic("acme", TopicNaming.DEAD_LETTER)
            em = rt.api("event-management").management("acme")
            # exactly the FIRST record handled by inbound is poison
            fi.arm("inbound.handle", rate=1.0, max_faults=1)
            p, off = await rt.bus.produce(decoded, _measurements(32, 1000.0),
                                          key="gw")
            # the poison record lands in the tenant DLQ with provenance
            await wait_until(lambda: len(rt.bus.peek(dlq)) == 1)
            rec, entry = list_dead_letters(rt.bus, dlq)[0]
            assert entry["original_topic"] == decoded
            assert (entry["partition"], entry["offset"]) == (p, off)
            assert entry["key"] == "gw"
            assert "inbound-processor" in entry["stage"]
            assert "FaultInjected" in entry["error"]
            assert isinstance(entry["value"], MeasurementBatch)
            assert rt.metrics.counter("dlq.quarantined").value == 1
            # the loop survived: the NEXT record flows through
            await rt.bus.produce(decoded, _measurements(32, 1001.0), key="gw")
            await wait_until(lambda: em.telemetry.total_events == 32)
            # replay re-produces the original value; it persists this time
            assert await replay_dead_letters(rt.bus, dlq) == 1
            await wait_until(lambda: em.telemetry.total_events == 64)
            # replay progress committed: a second replay is a no-op
            assert await replay_dead_letters(rt.bus, dlq) == 0
            await asyncio.sleep(0.1)
            assert em.telemetry.total_events == 64
        finally:
            await rt.stop()

    run(main())


def test_poison_record_does_not_kill_loop_without_faults(run):
    """A genuinely malformed record (not injected): handler raises,
    record is quarantined, pipeline keeps flowing."""
    async def main():
        rt, _fi = await _mini_runtime()
        try:
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            dlq = rt.naming.tenant_topic("acme", TopicNaming.DEAD_LETTER)
            em = rt.api("event-management").management("acme")
            poison = _measurements(8, 1000.0)
            # string device indices break the registration-mask gather
            poison.device_index = np.array(["x"] * 8, dtype=object)
            await rt.bus.produce(decoded, poison, key="gw")
            await rt.bus.produce(decoded, _measurements(32, 1001.0), key="gw")
            await wait_until(lambda: em.telemetry.total_events == 32)
            entries = list_dead_letters(rt.bus, dlq)
            assert len(entries) == 1
            svc = rt.services["inbound-processing"]
            proc = svc.engines["acme"].processor
            assert proc.status is LifecycleStatus.STARTED
        finally:
            await rt.stop()

    run(main())


# -- chaos integration ------------------------------------------------------

def test_chaos_pipeline_drains_and_stops(run, tmp_path):
    """FaultInjector raising at ≥3 distinct sites — bus poll handler,
    durable flush, scoring dispatch (plus a poison inbound record):
    crashed loops restart under budget, the poison record lands in the
    DLQ, every event is accounted for (persisted or quarantined —
    nothing silently lost), scoring keeps draining, and rt.stop()
    completes cleanly."""
    async def main():
        rt, fi = await _mini_runtime(tmp_path=tmp_path / "data", rule=True)
        try:
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            dlq = rt.naming.tenant_topic("acme", TopicNaming.DEAD_LETTER)
            em = rt.api("event-management").management("acme")
            session = rt.api("rule-processing").engine("acme").session
            await wait_until(lambda: session.ready, timeout=60.0)

            scored_topic = rt.naming.tenant_topic(
                "acme", TopicNaming.SCORED_EVENTS)

            def scored_events():
                # peek, not subscribe: an admin read consumes no fault
                # budget and joins no group
                return sum(
                    r.value.total_scored if r.value.total_scored >= 0
                    else len(r.value)
                    for r in rt.bus.peek(scored_topic, limit=-1))

            # arm AFTER setup so engine spin-up itself is not chaosed;
            # bounded injections keep every loop under its restart budget
            fi.arm("bus.poll", rate=0.02, max_faults=3)
            fi.arm("scoring.dispatch", rate=0.3, max_faults=3)
            fi.arm("durable.flush", rate=0.5, max_faults=3)
            # with rule-processing co-resident the fused fast lane
            # (kernel/fastlane.py) owns the decoded hop and consults its
            # own site; arm both so the per-record poison path fires
            # whichever lane handles the records (rate 0.1: the injector
            # is per-site seeded — fastlane.handle's seed-42 draw
            # sequence first fires within 40 records at ≥0.08)
            fi.arm("inbound.handle", rate=0.03, max_faults=2)
            fi.arm("fastlane.handle", rate=0.1, max_faults=2)

            n_batches, per_batch = 40, 32
            for k in range(n_batches):
                await rt.bus.produce(decoded,
                                     _measurements(per_batch, 2000.0 + k),
                                     key="gw")
                await asyncio.sleep(0.01)

            sent = n_batches * per_batch

            def quarantined():
                # decoded-hop quarantines carry the handling lane's
                # provenance: the staged inbound processor or the fused
                # fast lane (which serves this tenant here)
                return sum(len(e["value"]) for _, e in
                           list_dead_letters(rt.bus, dlq, limit=-1)
                           if "inbound-processor" in e["stage"]
                           or "fastlane" in e["stage"])

            # every event is accounted for: persisted or quarantined
            # (crash/restart redelivery may persist a record twice —
            # at-least-once — so >= on the persisted side)
            await wait_until(
                lambda: em.telemetry.total_events + quarantined() >= sent,
                timeout=30.0)
            assert quarantined() > 0, "no poison record was quarantined"

            # faults actually fired at all three required sites
            snap = fi.snapshot()
            for site in ("bus.poll", "scoring.dispatch", "durable.flush"):
                assert snap[site]["injected"] > 0, (site, snap)
            # ...and the supervisor restarted the crashed loops
            assert rt.metrics.counter("supervisor.restarts").value > 0
            # no loop exhausted its budget: everything still healthy
            def no_errors(node):
                assert node["status"] != "lifecycle_error", node
                for ch in node["children"]:
                    no_errors(ch)
            no_errors(rt.state_tree())

            # scoring drained: every persisted event scored at least once
            persisted = em.telemetry.total_events
            await wait_until(lambda: scored_events() >= persisted,
                             timeout=30.0)
            # durable writer survived its injected faults and kept writing
            end = fi.snapshot()
            assert em.durable.write_errors == end["durable.flush"]["injected"]
            assert em.durable.write_errors > 0
            assert em.durable.written > 0
        finally:
            await rt.stop()
        assert rt.status is LifecycleStatus.STOPPED

    run(main())
