"""End-to-end pipeline test: config 1 [BASELINE.json configs[0]].

simulator → event-sources(SWB1 decode) → inbound-processing(mask check) →
event-management(columnar persist) → device-state(merge), single tenant
[SURVEY.md §3.2, §7 step 2].
"""

import asyncio
import contextlib

import numpy as np

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.services import (
    DeviceManagementService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
)
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig


@contextlib.asynccontextmanager
async def running_pipeline(num_devices: int = 100, sections: dict | None = None,
                           extra_services: tuple = ()):
    """Started runtime with tenant 'acme' and a registered fleet."""
    from sitewhere_tpu.services import RuleProcessingService

    rt = ServiceRuntime(InstanceSettings(instance_id="e2e"))
    rt.add_service(DeviceManagementService(rt))
    rt.add_service(EventSourcesService(rt))
    rt.add_service(InboundProcessingService(rt))
    rt.add_service(EventManagementService(rt))
    rt.add_service(DeviceStateService(rt))
    if sections and "rule-processing" in sections:
        rt.add_service(RuleProcessingService(rt))
    for cls in extra_services:
        rt.add_service(cls(rt))
    await rt.start()
    await rt.add_tenant(TenantConfig(tenant_id="acme", sections=sections or {}))
    dm = rt.api("device-management").management("acme")
    dt = DeviceType(token="thermo", name="Thermometer", channels=("temp",))
    dm.bootstrap_fleet(dt, num_devices)
    try:
        yield rt
    finally:
        await rt.stop()


async def wait_until(predicate, timeout=10.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        if predicate():
            return
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError("condition not met")
        await asyncio.sleep(interval)


def test_e2e_swb1_ingest_to_state(run):
    async def main():
        async with running_pipeline() as rt:
            sim = DeviceSimulator(SimConfig(num_devices=100), tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme").receiver("default")
            for k in range(5):
                payload, _ = sim.payload(t=1000.0 + k)
                await receiver.submit(payload)

            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 500)

            # persisted history is chronological per device
            table = em.telemetry.channel(0)
            vals, valid = table.window(np.arange(100), 5)
            assert valid.all()
            tss = table.window_ts(np.arange(100), 5)
            np.testing.assert_array_equal(
                tss[0], [1000., 1001., 1002., 1003., 1004.])

            # device-state materialized the newest reading
            state_engine = rt.api("device-state").state("acme")
            await wait_until(
                lambda: state_engine.last_seen[:100].min() == 1004.0)
            st = state_engine.get_state(42)
            assert st["last_seen"] == 1004.0
            assert st["channels"][0]["ts"] == 1004.0
            np.testing.assert_allclose(st["channels"][0]["value"],
                                       vals[42, -1], rtol=1e-6)

    run(main())


def test_unregistered_devices_split_off(run):
    async def main():
        async with running_pipeline(num_devices=100) as rt:
            # simulate 150 devices but only 100 are registered
            sim = DeviceSimulator(SimConfig(num_devices=150), tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme").receiver("default")
            payload, _ = sim.payload(t=2000.0)
            await receiver.submit(payload)

            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 100)
            await asyncio.sleep(0.05)
            assert em.telemetry.total_events == 100  # unknown 50 never persist

            topic = rt.naming.tenant_topic("acme", "unregistered-device-events")
            assert sum(rt.bus.end_offsets(topic)) == 1

    run(main())


def test_json_decoder_and_failed_decode(run):
    async def main():
        async with running_pipeline() as rt:
            sources = rt.api("event-sources").engine("acme")
            sources.add_receiver(
                {"kind": "queue", "decoder": "json", "name": "json-in"})
            await sources.receiver("json-in").start()

            payload = (
                b'{"requests": ['
                b'{"type": "measurement", "device": "dev-7", "value": 33.5,'
                b' "ts": 3000},'
                b'{"type": "measurement", "device": "ghost", "value": 1.0},'
                b'{"type": "location", "device": "dev-8", "lat": 33.7,'
                b' "lon": -84.4}]}')
            await sources.receiver("json-in").submit(payload)

            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events >= 1)
            ms = em.list_measurements(7)
            assert [m.value for m in ms] == [33.5]
            locs = em.list_locations(8)
            assert len(locs) == 1 and abs(locs[0].latitude - 33.7) < 1e-9

            # garbage payload → failed-decode topic, pipeline stays up
            await sources.receiver("json-in").submit(b"\x00garbage")
            failed = rt.naming.tenant_topic(
                "acme", "event-source-failed-decode-events")
            await wait_until(lambda: sum(rt.bus.end_offsets(failed)) == 1)

    run(main())


def test_tcp_receiver_roundtrip(run):
    async def main():
        async with running_pipeline(num_devices=10) as rt:
            sim = DeviceSimulator(SimConfig(num_devices=10), tenant_id="acme")
            sources = rt.api("event-sources").engine("acme")
            tcp = sources.add_receiver(
                {"kind": "tcp", "decoder": "swb1", "name": "tcp-in"})
            await tcp.start()
            payload, _ = sim.payload(t=4000.0)
            reader, writer = await asyncio.open_connection("127.0.0.1", tcp.port)
            writer.write(len(payload).to_bytes(4, "little") + payload)
            await writer.drain()
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events >= 10)
            writer.close()

    run(main())


def test_simulator_anomaly_injection():
    sim = DeviceSimulator(SimConfig(num_devices=5000, anomaly_rate=0.02,
                                    anomaly_magnitude=10.0), tenant_id="t")
    batch, truth = sim.tick(t=0.0)
    assert 0.005 < truth.mean() < 0.06
    # anomalous readings are far from their own device's baseline
    # (amplitude ≤ 3, noise σ=0.15, injected magnitude 10)
    own_base = sim.base[batch.device_index.astype(np.int64)]
    deviation = np.abs(batch.value - own_base)
    assert deviation[truth].min() > 5.0
    assert deviation[~truth].max() < 5.0


def test_pipeline_spans_recorded(run):
    """§5.1: sampled traces leave one span per pipeline stage, queryable
    by trace id (decode → enrich → persist → score)."""

    async def main():
        from tests.test_pipeline import running_pipeline, wait_until
        sections = {"rule-processing": {"model": "zscore",
                                        "model_config": {"window": 16},
                                        "batch_window_ms": 1.0}}
        async with running_pipeline(num_devices=20, sections=sections) as rt:
            rt.tracer.sample = 1  # record every trace for the test
            from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig
            sim = DeviceSimulator(SimConfig(num_devices=20), tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme").receiver("default")
            session = rt.api("rule-processing").engine("acme").session
            for k in range(20):
                await receiver.submit(sim.payload(t=60.0 * k)[0])
            await wait_until(lambda: session.latency.count >= 400)
            summary = rt.tracer.stage_summary()
            for stage in ("event-sources.decode", "inbound.enrich",
                          "event-management.persist", "rule-processing.score"):
                assert stage in summary, (stage, summary.keys())
                assert summary[stage]["events"] > 0
            # one trace's journey is ordered receive → decode → ... → score
            scored = [s for s in rt.tracer.spans("rule-processing.score")
                      if s.n_events > 0]
            journey = rt.tracer.trace(scored[0].trace_id)
            stages = [s.stage for s in journey]
            assert stages.index("event-sources.receive") == 0
            assert stages.index("event-sources.decode") == 1
            assert "event-management.persist" in stages

    run(main())
