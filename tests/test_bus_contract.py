"""Bus CONTRACT tests: one suite, every implementation.

The services only assume the produce/subscribe Protocol; these tests pin
the semantics every implementation must honor — per-key ordering,
consumer groups, commit/resume at-least-once, long-poll wake — and run
them against:

- the in-proc asyncio bus (kernel/bus.py)
- the wire bus (BusServer + RemoteEventBus over real sockets)
- the Kafka adapter (kernel/kafka.py) — against a real broker when
  SWX_KAFKA_BOOTSTRAP is set, else against the in-repo aiokafka fake
  (kernel/fake_kafka.py), so the adapter's logic always executes.
"""

import asyncio
import contextlib
import itertools
import os

import pytest


@contextlib.asynccontextmanager
async def inproc_bus():
    from sitewhere_tpu.kernel.bus import EventBus

    bus = EventBus(default_partitions=4)
    await bus.initialize()
    await bus.start()
    try:
        yield bus
    finally:
        await bus.stop()


@contextlib.asynccontextmanager
async def wire_bus():
    from sitewhere_tpu.kernel.bus import EventBus
    from sitewhere_tpu.kernel.wire import BusServer, RemoteEventBus

    backing = EventBus(default_partitions=4)
    await backing.initialize()
    await backing.start()
    server = BusServer(backing)
    await server.start()
    remote = RemoteEventBus("127.0.0.1", server.port)
    await remote.initialize()
    try:
        yield remote
    finally:
        await remote.stop()
        await server.stop()
        await backing.stop()


_fake_broker_seq = itertools.count()


@contextlib.asynccontextmanager
async def kafka_bus():
    """KafkaEventBus rows: against a real broker when the env provides
    one (SWX_KAFKA_BOOTSTRAP), else against the in-repo aiokafka fake —
    the ADAPTER's logic (serializers, commit maps, poll loop, rebalance)
    runs either way, so these rows never skip."""
    from sitewhere_tpu.kernel.kafka import KafkaEventBus

    bootstrap = os.environ.get("SWX_KAFKA_BOOTSTRAP")
    if bootstrap is not None:
        try:
            bus = KafkaEventBus(bootstrap)
        except RuntimeError as exc:
            pytest.skip(str(exc))
    else:
        from sitewhere_tpu.kernel import fake_kafka

        # unique bootstrap per case: isolated fake-broker state
        bus = KafkaEventBus(f"fake-{next(_fake_broker_seq)}:9092",
                            client_mod=fake_kafka)
    await bus.initialize()
    try:
        yield bus
    finally:
        await bus.stop()


IMPLS = {"inproc": inproc_bus, "wire": wire_bus, "kafka": kafka_bus}


async def _maybe(v):
    import inspect

    return await v if inspect.isawaitable(v) else v


@pytest.fixture(params=list(IMPLS))
def bus_impl(request):
    return IMPLS[request.param]


def test_contract_per_key_ordering(run, bus_impl):
    async def main():
        async with bus_impl() as bus:
            for i in range(20):
                await bus.produce("c-order", {"seq": i}, key="device-7")
            c = bus.subscribe("c-order", group="g1")
            seen = []
            while len(seen) < 20:
                for r in await c.poll(max_records=64, timeout=5.0):
                    seen.append(r.value["seq"])
            assert seen == list(range(20))  # one key → one partition, FIFO
            c.close()

    run(main())


def test_contract_commit_resume_at_least_once(run, bus_impl):
    async def main():
        async with bus_impl() as bus:
            for i in range(10):
                await bus.produce("c-resume", {"i": i}, key="k")
            c = bus.subscribe("c-resume", group="g2")
            got = []
            while len(got) < 10:
                got += [r.value["i"] for r in
                        await c.poll(max_records=4, timeout=5.0)]
                if len(got) == 4:
                    c.commit()  # only the first 4 committed
                    await asyncio.sleep(0.1)
            c.close()
            await asyncio.sleep(0.1)
            c2 = bus.subscribe("c-resume", group="g2")
            redelivered = []
            while len(redelivered) < 6:
                redelivered += [r.value["i"] for r in
                                await c2.poll(max_records=64, timeout=5.0)]
            assert redelivered[0] == 4  # resumes at last commit
            assert redelivered == [4, 5, 6, 7, 8, 9]
            c2.close()

    run(main())


def test_contract_independent_groups(run, bus_impl):
    async def main():
        async with bus_impl() as bus:
            for i in range(5):
                await bus.produce("c-groups", i, key="k")
            a = bus.subscribe("c-groups", group="ga")
            b = bus.subscribe("c-groups", group="gb")
            for c in (a, b):
                got = []
                while len(got) < 5:
                    got += [r.value for r in
                            await c.poll(max_records=64, timeout=5.0)]
                assert got == [0, 1, 2, 3, 4]
                c.close()

    run(main())


def test_contract_long_poll_wakes_on_produce(run, bus_impl):
    async def main():
        async with bus_impl() as bus:
            c = bus.subscribe("c-wake", group="gw")
            await c.poll(max_records=1, timeout=0.2)  # assignment settles

            async def later():
                await asyncio.sleep(0.1)
                await bus.produce("c-wake", "ping", key="k")

            t = asyncio.get_running_loop().create_task(later())
            t0 = asyncio.get_running_loop().time()
            records = []
            while not records:
                records = await c.poll(max_records=10, timeout=10.0)
            waited = asyncio.get_running_loop().time() - t0
            await t
            assert [r.value for r in records] == ["ping"]
            assert waited < 5.0  # woke on produce, not the poll timeout
            c.close()

    run(main())


# -- background-op retention (swx lint TSK01 regression) ---------------------


def test_spawn_logged_retains_and_surfaces_failures(run, caplog):
    """`_spawn_logged` is the adapter's retained fire-and-forget: the
    task set holds the strong reference the event loop does not (an
    unretained task can be GC'd mid-flight), and a failed background op
    lands in the log instead of dying with an unretrieved exception —
    pre-fix, `produce_nowait`/`commit`/`close` dropped the handle."""
    import logging

    from sitewhere_tpu.kernel.kafka import _spawn_logged

    async def main():
        tasks: set = set()
        gate = asyncio.Event()

        async def held():
            await gate.wait()

        t = _spawn_logged(tasks, held())
        assert t in tasks          # strong ref while in flight
        gate.set()
        await t
        await asyncio.sleep(0)
        assert t not in tasks      # done callback prunes the set

        async def boom():
            raise RuntimeError("background op exploded")

        t2 = _spawn_logged(tasks, boom())
        await t2                   # _log_failure retrieves + logs
        await asyncio.sleep(0)
        assert t2 not in tasks

    with caplog.at_level(logging.ERROR, logger="sitewhere_tpu.kernel.kafka"):
        run(main())
    assert any("background operation failed" in r.getMessage()
               for r in caplog.records)


def test_kafka_produce_nowait_task_is_retained(run):
    async def main():
        async with kafka_bus() as bus:
            bus.produce_nowait("c-bg", {"i": 1}, key="k")
            assert bus._bg  # in-flight background produce strongly held
            while bus._bg:  # drains once the produce settles
                await asyncio.sleep(0.01)

    run(main())
