"""Adversarial-frame suite for the wire codec (kernel/codec.py).

The contract under attack: ANY malformed frame — truncation at every
byte, bogus tags, length prefixes that lie (past the frame, past
MAX_FRAME), unregistered class names, ndarray headers whose dtype/shape
disagree with their payload — raises the TYPED `WireFormatError` (a
ValueError), and no partially-constructed object escapes. The zero-copy
decode path (`copy_arrays=False`, what the wire rx loops run) must pass
the identical suite."""

import dataclasses
import struct

import numpy as np
import pytest

from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.kernel import codec
from sitewhere_tpu.kernel.codec import WireFormatError

BOTH_MODES = pytest.mark.parametrize("copy_arrays", [True, False],
                                     ids=["copy", "zero-copy"])


def _sample_payload() -> bytes:
    ctx = BatchContext(tenant_id="t", source="s", trace_id=7)
    batch = MeasurementBatch(
        ctx, np.arange(512, dtype=np.uint32),
        np.zeros(512, np.uint16),
        np.linspace(0, 1, 512).astype(np.float32),
        np.full(512, 1700000000.0))
    return codec.encode({"op": "produce", "topic": "x", "value": batch,
                         "key": "s", "meta": [1, (2.5, None), {"a": b"b"}]})


@BOTH_MODES
def test_truncation_at_every_boundary_is_typed(copy_arrays):
    """Cutting the frame anywhere raises WireFormatError — never a bare
    struct.error / IndexError, never a partial value."""
    payload = _sample_payload()
    # every prefix of a real frame, stepped to keep the suite fast but
    # covering every header/length/payload boundary region
    cuts = set(range(0, 64)) \
        | set(range(64, len(payload), 97)) | {len(payload) - 1}
    for cut in sorted(cuts):
        with pytest.raises(ValueError) as exc_info:
            codec.decode(payload[:cut], copy_arrays=copy_arrays)
        assert isinstance(exc_info.value, (WireFormatError,)), (
            f"cut at {cut} raised untyped {exc_info.value!r}")


@BOTH_MODES
def test_bogus_tags_refused(copy_arrays):
    for tag in (13, 42, 200, 255):
        with pytest.raises(WireFormatError):
            codec.decode(bytes([tag]) + b"\x00" * 16,
                         copy_arrays=copy_arrays)


@BOTH_MODES
def test_oversized_length_prefix_refused(copy_arrays):
    # a tiny frame claiming a huge string/bytes body: the prefix check
    # must fire before any allocation or read
    for tag in (codec.T_STR, codec.T_BYTES):
        huge = bytes([tag]) + struct.pack("<I", 0xFFFFFFF0) + b"x"
        with pytest.raises(WireFormatError):
            codec.decode(huge, copy_arrays=copy_arrays)
    # ... and a prefix past MAX_FRAME is refused even if somehow backed
    claim = bytes([codec.T_BYTES]) + struct.pack("<I", codec.MAX_FRAME + 1)
    with pytest.raises(WireFormatError):
        codec.decode(claim, copy_arrays=copy_arrays)
    # container counts lie too: a list claiming 2^31 elements dies on
    # the bounds gate, not after looping
    biglist = bytes([codec.T_LIST]) + struct.pack("<I", 0x7FFFFFFF)
    with pytest.raises(WireFormatError):
        codec.decode(biglist, copy_arrays=copy_arrays)


@BOTH_MODES
def test_unregistered_dataclass_and_enum_refused(copy_arrays):
    payload = bytearray(codec.encode(BatchContext(tenant_id="t")))
    payload = payload.replace(b"BatchContext", b"EvilClsNeverX")
    with pytest.raises(WireFormatError):
        codec.decode(bytes(payload), copy_arrays=copy_arrays)
    from sitewhere_tpu.domain.events import AlertLevel, DeviceAlert

    enc = bytearray(codec.encode(DeviceAlert(level=AlertLevel.ERROR,
                                             message="hot")))
    enc = enc.replace(b"AlertLevel", b"EvilLevelX")
    with pytest.raises(WireFormatError):
        codec.decode(bytes(enc), copy_arrays=copy_arrays)


@BOTH_MODES
def test_dataclass_field_mismatch_no_partial_construction(copy_arrays):
    """A registered class name with hostile field names must raise
    typed — the class is never constructed with garbage kwargs."""
    constructed = []

    @dataclasses.dataclass
    class _CanaryNeverBuilt:
        x: int = 0

        def __post_init__(self):
            constructed.append(self)

    codec.register_class(_CanaryNeverBuilt)
    try:
        payload = bytearray(codec.encode(_CanaryNeverBuilt(x=1)))
        # rename the field: x -> q (same length keeps offsets valid)
        idx = payload.rindex(b"\x01\x00\x00\x00x")
        payload[idx + 4:idx + 5] = b"q"
        constructed.clear()
        with pytest.raises(WireFormatError):
            codec.decode(bytes(payload), copy_arrays=copy_arrays)
        assert not constructed, "partial construction escaped"
    finally:
        codec._CLASSES.pop("_CanaryNeverBuilt", None)


@BOTH_MODES
def test_enum_bad_value_refused(copy_arrays):
    from sitewhere_tpu.domain.events import AlertLevel

    enc = bytearray(codec.encode(AlertLevel.ERROR))
    # the enum value rides as a tagged scalar at the tail — replace it
    # with an int no AlertLevel maps to
    enc[-8:] = struct.pack("<q", 2 ** 40)
    with pytest.raises(WireFormatError):
        codec.decode(bytes(enc), copy_arrays=copy_arrays)


@BOTH_MODES
def test_ndarray_dtype_lying_headers_refused(copy_arrays):
    a = np.arange(16, dtype=np.float32)
    good = bytearray(codec.encode(a))

    def mutated(offset, repl):
        out = bytearray(good)
        out[offset:offset + len(repl)] = repl
        return bytes(out)

    # layout: tag | u32 dtype-len | dtype | u32 ndim | u32 dim | u32 nbytes
    dlen = struct.unpack_from("<I", good, 1)[0]
    dim_off = 1 + 4 + dlen + 4
    nbytes_off = dim_off + 4
    # (a) shape lies: claims 17 elements over a 16-element payload
    with pytest.raises(WireFormatError):
        codec.decode(mutated(dim_off, struct.pack("<I", 17)),
                     copy_arrays=copy_arrays)
    # (b) nbytes lies vs shape × itemsize
    with pytest.raises(WireFormatError):
        codec.decode(mutated(nbytes_off, struct.pack("<I", 60)),
                     copy_arrays=copy_arrays)
    # (c) dtype string lies about width: <f8 over 16 f4 elements makes
    # shape × itemsize disagree with the 64-byte payload
    with pytest.raises(WireFormatError):
        codec.decode(bytes(good).replace(b"<f4", b"<f8"),
                     copy_arrays=copy_arrays)
    # (d) garbage dtype string
    with pytest.raises(WireFormatError):
        codec.decode(bytes(good).replace(b"<f4", b"@@@"),
                     copy_arrays=copy_arrays)
    # (e) object dtype is refused outright (the pickle hole)
    with pytest.raises(WireFormatError):
        codec.decode(bytes(good).replace(b"<f4", b"|O1"),
                     copy_arrays=copy_arrays)
    # (f) absurd ndim
    with pytest.raises(WireFormatError):
        codec.decode(mutated(1 + 4 + dlen, struct.pack("<I", 10 ** 6)),
                     copy_arrays=copy_arrays)


@BOTH_MODES
def test_trailing_bytes_refused(copy_arrays):
    with pytest.raises(WireFormatError):
        codec.decode(codec.encode({"a": 1}) + b"\x00",
                     copy_arrays=copy_arrays)


@BOTH_MODES
def test_good_frames_still_roundtrip(copy_arrays):
    """The hardening must not reject a single honest frame — the full
    round trip from tests/test_wire.py, in both copy modes."""
    payload = _sample_payload()
    out = codec.decode(payload, copy_arrays=copy_arrays)
    batch = out["value"]
    np.testing.assert_array_equal(batch.device_index,
                                  np.arange(512, dtype=np.uint32))
    np.testing.assert_array_equal(
        batch.value, np.linspace(0, 1, 512).astype(np.float32))
    assert batch.ctx.trace_id == 7
    assert out["meta"] == [1, (2.5, None), {"a": b"b"}]
    if not copy_arrays:
        # the zero-copy contract: views over the frame, read-only
        assert not batch.value.flags.writeable
        with pytest.raises(ValueError):
            batch.value[0] = 9.0


def test_segments_equal_bytes():
    """encode_segments is byte-identical to encode (the scatter-gather
    path changes the write shape, never the wire format)."""
    values = [None, {"k": [1, 2.5, "s", b"b"]},
              np.arange(4096, dtype=np.float32),   # SG-eligible column
              np.arange(3, dtype=np.int64),        # below the SG floor
              _sample_payload_value()]
    for v in values:
        segs, total = codec.encode_segments(v)
        joined = b"".join(bytes(s) for s in segs)
        assert len(joined) == total
        assert joined == codec.encode(v)


def _sample_payload_value():
    ctx = BatchContext(tenant_id="t", source="s", trace_id=3)
    return MeasurementBatch(
        ctx, np.arange(2048, dtype=np.uint32),
        np.zeros(2048, np.uint16),
        np.linspace(0, 1, 2048).astype(np.float32),
        np.full(2048, 1700000000.0))
