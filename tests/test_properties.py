"""Property tests on the kernel promises (SURVEY.md §5.2).

The lifecycle state machine and the bus are the two contracts everything
else leans on; example-based tests pin happy paths, these pin the
INVARIANTS under arbitrary operation sequences (hypothesis):

- lifecycle: illegal transitions raise and leave state untouched; no
  component is ever left in a transitional (*-ING) state at rest; stop
  stops every descendant; a component survives any op sequence and can
  always be recovered to STARTED; concurrent start/stop interleavings
  never wedge the component.
- bus: per-key ordering holds across consumer-group rebalances;
  committed offsets are monotonic, including under retention trim; a
  trim past the committed offset resets forward (never backward) and
  consumption covers everything still retained (at-least-once).
"""

import asyncio

import numpy as np
import pytest

# pre-existing tier-1 noise fix: absent hypothesis must SKIP this module
# at collection, not fail it (the image does not guarantee hypothesis)
pytest.importorskip("hypothesis")

from hypothesis import (  # noqa: E402 - after the importorskip gate
    HealthCheck,
    given,
    settings,
    strategies as st,
)

from sitewhere_tpu.kernel.bus import EventBus
from sitewhere_tpu.kernel.lifecycle import (
    LifecycleComponent,
    LifecycleException,
    LifecycleStatus,
)

RESTING = {LifecycleStatus.STOPPED, LifecycleStatus.INITIALIZED,
           LifecycleStatus.STARTED, LifecycleStatus.PAUSED,
           LifecycleStatus.TERMINATED,
           LifecycleStatus.INITIALIZATION_ERROR,
           LifecycleStatus.LIFECYCLE_ERROR}


class _Probe(LifecycleComponent):
    """Component that yields control inside transitions (so concurrent
    interleavings actually interleave) and counts hook invocations."""

    def __init__(self, name: str):
        super().__init__(name)
        self.calls = {"init": 0, "start": 0, "stop": 0}

    async def _do_initialize(self, monitor) -> None:
        self.calls["init"] += 1
        await asyncio.sleep(0)

    async def _do_start(self, monitor) -> None:
        self.calls["start"] += 1
        await asyncio.sleep(0)

    async def _do_stop(self, monitor) -> None:
        self.calls["stop"] += 1
        await asyncio.sleep(0)


def _tree() -> tuple[_Probe, list[_Probe]]:
    root = _Probe("root")
    kids = [_Probe(f"kid{i}") for i in range(3)]
    for k in kids:
        root.add_child(k)
    grand = _Probe("grandkid")
    kids[1].add_child(grand)
    return root, kids + [grand]


OPS = ("initialize", "start", "stop", "restart", "terminate")


@settings(max_examples=60, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(ops=st.lists(st.sampled_from(OPS), min_size=1, max_size=12))
def test_lifecycle_any_sequence_keeps_invariants(ops):
    async def main():
        root, descendants = _tree()
        for op in ops:
            before = root.status
            try:
                await getattr(root, op)()
            except LifecycleException:
                # an illegal transition must not have moved the state
                assert root.status == before, (op, before, root.status)
            # never at rest in a transitional state
            assert root.status in RESTING, (op, root.status)
            for d in descendants:
                assert d.status in RESTING, (op, d.status)
            if root.status == LifecycleStatus.STARTED:
                assert all(d.status == LifecycleStatus.STARTED
                           for d in descendants)
            if root.status == LifecycleStatus.STOPPED and "stop" == op:
                assert all(d.status in (LifecycleStatus.STOPPED,
                                        LifecycleStatus.INITIALIZED,
                                        LifecycleStatus.TERMINATED)
                           for d in descendants)
        # recovery invariant: unless terminated, the component can always
        # be brought to STARTED
        if root.status != LifecycleStatus.TERMINATED:
            if root.status in (LifecycleStatus.STARTED,
                               LifecycleStatus.PAUSED,
                               LifecycleStatus.STARTING):
                await root.stop()
            await root.start()
            assert root.status == LifecycleStatus.STARTED

    asyncio.run(main())


@settings(max_examples=40, deadline=None)
@given(first=st.sampled_from(["start", "stop"]),
       start_state=st.sampled_from(["initialized", "started"]))
def test_lifecycle_concurrent_start_stop_never_wedges(first, start_state):
    """Concurrent start()/stop() — the respin-during-update interleaving
    — may raise LifecycleException in one task, but must leave the tree
    recoverable and never resting in a transitional state."""

    async def main():
        root, descendants = _tree()
        await root.initialize()
        if start_state == "started":
            await root.start()
        a = root.start() if first == "start" else root.stop()
        b = root.stop() if first == "start" else root.start()
        results = await asyncio.gather(a, b, return_exceptions=True)
        for r in results:
            assert r is None or isinstance(r, LifecycleException), r
        assert root.status in RESTING
        # recoverable regardless of who won the race
        if root.status in (LifecycleStatus.STARTED, LifecycleStatus.PAUSED):
            await root.stop()
        await root.start()
        assert root.status == LifecycleStatus.STARTED
        assert all(d.status == LifecycleStatus.STARTED for d in descendants)
        await root.stop()

    asyncio.run(main())


# -- bus invariants ----------------------------------------------------------


@settings(max_examples=30, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(data=st.data())
def test_bus_per_key_order_survives_rebalances(data):
    """Random interleaving of produces and consumer joins/leaves: each
    key's records are observed in sequence order by whoever owns its
    partition (duplicates allowed — at-least-once), with no reordering
    and no loss."""

    keys = ["alpha", "beta", "gamma", "delta"]
    script = data.draw(st.lists(
        st.one_of(
            st.tuples(st.just("produce"), st.sampled_from(keys)),
            st.just(("join",)),
            st.just(("leave",)),
        ), min_size=8, max_size=40))

    async def main():
        bus = EventBus(default_partitions=4)
        seq = {k: 0 for k in keys}
        consumers = []
        seen: dict[str, list[int]] = {k: [] for k in keys}

        async def drain(c):
            for r in await c.poll(max_records=512, timeout=0.05):
                seen[r.key].append(r.value)
            c.commit()

        consumers.append(bus.subscribe("t", group="g"))
        for op in script:
            if op[0] == "produce":
                k = op[1]
                await bus.produce("t", seq[k], key=k)
                seq[k] += 1
            elif op[0] == "join" and len(consumers) < 4:
                consumers.append(bus.subscribe("t", group="g"))
            elif op[0] == "leave" and len(consumers) > 1:
                # drain before leaving so nothing is lost uncommitted
                c = consumers.pop()
                await drain(c)
                c.close()
            for c in consumers:
                await drain(c)
        for _ in range(3):
            for c in consumers:
                await drain(c)
        for c in consumers:
            c.close()
        for k in keys:
            got = seen[k]
            # per-key order: non-decreasing with no skips between
            # consecutive NEW values (dups from redelivery are legal)
            dedup = []
            for v in got:
                if not dedup or v > dedup[-1]:
                    dedup.append(v)
                else:
                    assert v <= dedup[-1]  # a redelivery, never the future
            assert dedup == list(range(seq[k])), (k, got)

    asyncio.run(main())


@settings(max_examples=30, deadline=None)
@given(batches=st.lists(st.integers(min_value=1, max_value=30),
                        min_size=1, max_size=8),
       retention=st.integers(min_value=4, max_value=16))
def test_bus_commit_monotonic_under_trim(batches, retention):
    """Produce in bursts against a tiny retention window, polling and
    committing between bursts: the committed offset never decreases, a
    consumer reset lands AT the trimmed base (never before), and every
    record still retained at poll time is delivered."""

    async def main():
        bus = EventBus(default_partitions=1, retention=retention)
        c = bus.subscribe("t", group="g")
        group = bus._groups["g"]
        produced = 0
        last_commit = 0
        for burst in batches:
            for _ in range(burst):
                await bus.produce("t", produced, key="k")
                produced += 1
            got = await c.poll(max_records=512, timeout=0.05)
            log = bus._topics["t"].partitions[0]
            if got:
                # delivery resumes at max(position, trimmed base)
                assert got[0].value >= last_commit
                assert got[0].offset >= log.base_offset - len(got) \
                    or got[0].offset >= 0
                # contiguous within the poll
                values = [r.value for r in got]
                assert values == list(range(values[0],
                                            values[0] + len(values)))
                # everything still retained was delivered up to the end
                assert got[-1].offset == log.end_offset - 1
            c.commit()
            committed = group.committed.get(("t", 0), 0)
            assert committed >= last_commit  # monotone, even after trim
            last_commit = committed
        c.close()

    asyncio.run(main())


def test_tenant_respin_during_update_lands_on_last_config(run):
    """Back-to-back tenant updates (the respin-during-update
    interleaving): the surviving engine is STARTED and built from the
    LAST config."""

    async def main():
        from sitewhere_tpu.config import InstanceSettings, TenantConfig
        from sitewhere_tpu.kernel.service import ServiceRuntime
        from sitewhere_tpu.services import DeviceManagementService

        rt = ServiceRuntime(InstanceSettings(instance_id="respin"))
        rt.add_service(DeviceManagementService(rt))
        await rt.start()
        await rt.add_tenant(TenantConfig(tenant_id="acme"))
        cfgs = [TenantConfig(tenant_id="acme",
                             sections={"device-management": {"rev": i}})
                for i in range(1, 6)]
        await asyncio.gather(*(rt.update_tenant(c) for c in cfgs))
        # whichever update raced last through the broadcast, the engine
        # at rest is STARTED and equivalent to the runtime's view
        eng = rt.services["device-management"].engines["acme"]
        assert eng.status == LifecycleStatus.STARTED
        assert eng.tenant.equivalent(rt.tenants["acme"])
        await rt.stop()

    run(main())


# -- geofence polygon containment (hypothesis) -------------------------------


@given(
    center=st.tuples(st.floats(-80, 80), st.floats(-170, 170)),
    radius=st.floats(0.1, 5.0),
    n_vertices=st.integers(3, 12),
    points=st.lists(st.tuples(st.floats(0.0, 2.0), st.floats(0, 2 * 3.14159)),
                    min_size=1, max_size=20),
)
@settings(max_examples=60, deadline=None)
def test_point_in_polygon_regular_polygon_radius_property(
        center, radius, n_vertices, points):
    """For a REGULAR convex polygon, containment is decidable by radius
    alone (away from the boundary band): points well inside the
    inscribed circle are in, points beyond the circumscribed circle are
    out — a geometry-only oracle independent of the ray-casting code."""
    import math

    from sitewhere_tpu.services.geofence import points_in_polygon

    cy, cx = center
    verts = tuple(
        (cy + radius * math.sin(2 * math.pi * k / n_vertices),
         cx + radius * math.cos(2 * math.pi * k / n_vertices))
        for k in range(n_vertices))
    r_in = radius * math.cos(math.pi / n_vertices)   # inscribed
    lat, lon, expect = [], [], []
    for rf, theta in points:
        rr = rf * radius
        # skip the ambiguous band between inscribed and circumscribed
        if 0.95 * r_in < rr < 1.05 * radius:
            continue
        lat.append(cy + rr * math.sin(theta))
        lon.append(cx + rr * math.cos(theta))
        expect.append(rr < r_in)
    if not lat:
        return
    got = points_in_polygon(np.asarray(lat), np.asarray(lon), verts)
    assert got.tolist() == expect


@given(
    verts=st.lists(st.tuples(st.integers(-50, 50), st.integers(-50, 50)),
                   min_size=3, max_size=10),
    pts=st.lists(st.tuples(st.integers(-60, 60), st.integers(-60, 60)),
                 min_size=1, max_size=10),
    shift=st.tuples(st.integers(-100, 100), st.integers(-100, 100)),
)
@settings(max_examples=60, deadline=None)
def test_point_in_polygon_translation_invariant(verts, pts, shift):
    """Containment is invariant under translating polygon AND points —
    catches coordinate-mixing bugs for arbitrary (self-intersecting
    included) polygons. Grid coordinates keep the arithmetic exact so
    boundary points (where ray casting is documented as unspecified)
    can be excluded exactly."""
    from sitewhere_tpu.services.geofence import points_in_polygon

    def on_boundary(p):
        py, px = p
        e = len(verts)
        for k in range(e):
            ay, ax = verts[k]
            by, bx = verts[(k + 1) % e]
            cross = (bx - ax) * (py - ay) - (by - ay) * (px - ax)
            if cross == 0 and min(ay, by) <= py <= max(ay, by) \
                    and min(ax, bx) <= px <= max(ax, bx):
                return True
        return False

    keep = [p for p in pts if not on_boundary(p)]
    if not keep:
        return
    lat = np.asarray([float(p[0]) for p in keep])
    lon = np.asarray([float(p[1]) for p in keep])
    a = points_in_polygon(lat, lon, tuple(verts))
    dy, dx = shift
    moved = tuple((y + dy, x + dx) for y, x in verts)
    b = points_in_polygon(lat + dy, lon + dx, moved)
    assert a.tolist() == b.tolist()
