"""Kafka wire-protocol endpoint (kernel/kafka_endpoint.py).

Exercised the way every other hosted protocol endpoint is — a
hand-rolled wire client speaking the classic protocol over a real
socket, plus fuzz — since no Kafka client library exists in this
image. Pins: produce/fetch round trips (codec objects AND foreign raw
bytes), offsets (earliest/latest/out-of-range after trim), long-poll
fetch, group offsets SHARED with in-proc consumer groups, and
survival under mutated frames.
"""

import asyncio
import struct
import zlib

import numpy as np

from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.kernel import codec
from sitewhere_tpu.kernel.bus import EventBus
from sitewhere_tpu.kernel.kafka_endpoint import (
    KafkaEndpoint,
    decode_message_set,
    encode_message_set,
)

from tests.test_pipeline import wait_until


# -- minimal hand-rolled classic-protocol client ----------------------------

def _s(v):
    if v is None:
        return struct.pack(">h", -1)
    b = v.encode()
    return struct.pack(">h", len(b)) + b


def _b(v):
    if v is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(v)) + v


class KafkaWireClient:
    def __init__(self, host, port):
        self.host, self.port = host, port
        self._corr = 0

    async def connect(self):
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port)

    async def _call(self, api_key, body: bytes) -> memoryview:
        self._corr += 1
        req = (struct.pack(">hhi", api_key, 0, self._corr)
               + _s("swx-test") + body)
        self.writer.write(struct.pack(">i", len(req)) + req)
        await self.writer.drain()
        size = struct.unpack(">i", await self.reader.readexactly(4))[0]
        payload = await self.reader.readexactly(size)
        corr = struct.unpack(">i", payload[:4])[0]
        assert corr == self._corr
        return memoryview(payload)[4:]

    async def api_versions(self):
        mv = await self._call(18, b"")
        err, n = struct.unpack_from(">hi", mv, 0)
        assert err == 0
        return [struct.unpack_from(">hhh", mv, 6 + 6 * i)
                for i in range(n)]

    async def metadata(self, *topics):
        body = struct.pack(">i", len(topics)) + b"".join(
            _s(t) for t in topics)
        return bytes(await self._call(3, body))

    async def produce(self, topic, partition, entries):
        """entries: [(key_bytes|None, value_bytes|None)]"""
        mset = encode_message_set(
            [(0, k, v, 0) for k, v in entries])
        body = (struct.pack(">hi", 1, 5000) + struct.pack(">i", 1)
                + _s(topic) + struct.pack(">i", 1)
                + struct.pack(">i", partition) + _b(mset))
        mv = await self._call(0, body)
        # parse: [topics] -> name, [parts] -> id, err, base
        off = 4
        nlen = struct.unpack_from(">h", mv, off)[0]
        off += 2 + nlen + 4
        pid, err, base = struct.unpack_from(">ihq", mv, off)
        return err, base

    async def fetch(self, topic, partition, offset, max_wait_ms=0,
                    min_bytes=0, max_bytes=1 << 20):
        body = (struct.pack(">iii", -1, max_wait_ms, min_bytes)
                + struct.pack(">i", 1) + _s(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iqi", partition, offset, max_bytes))
        mv = await self._call(1, body)
        off = 4
        nlen = struct.unpack_from(">h", mv, off)[0]
        off += 2 + nlen + 4
        pid, err, hwm = struct.unpack_from(">ihq", mv, off)
        off += 14
        mset_len = struct.unpack_from(">i", mv, off)[0]
        off += 4
        msgs = decode_message_set(mv[off:off + max(mset_len, 0)])
        return err, hwm, msgs

    async def list_offsets(self, topic, partition, ts, max_n=1):
        body = (struct.pack(">i", -1) + struct.pack(">i", 1) + _s(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iqi", partition, ts, max_n))
        mv = await self._call(2, body)
        off = 4
        nlen = struct.unpack_from(">h", mv, off)[0]
        off += 2 + nlen + 4
        pid, err = struct.unpack_from(">ih", mv, off)
        off += 6
        n = struct.unpack_from(">i", mv, off)[0]
        offs = [struct.unpack_from(">q", mv, off + 4 + 8 * i)[0]
                for i in range(n)]
        return err, offs

    async def offset_commit(self, group, topic, partition, offset):
        body = (_s(group) + struct.pack(">i", 1) + _s(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iq", partition, offset) + _s(""))
        return bytes(await self._call(8, body))

    async def offset_fetch(self, group, topic, partition):
        body = (_s(group) + struct.pack(">i", 1) + _s(topic)
                + struct.pack(">i", 1) + struct.pack(">i", partition))
        mv = await self._call(9, body)
        off = 4
        nlen = struct.unpack_from(">h", mv, off)[0]
        off += 2 + nlen + 4
        pid, offset = struct.unpack_from(">iq", mv, off)
        return offset

    async def close(self):
        self.writer.close()


def _mk_batch(n=4):
    return MeasurementBatch(
        BatchContext(tenant_id="acme", source="kafka-test"),
        np.arange(n, dtype=np.uint32), np.zeros(n, np.uint16),
        np.arange(n, dtype=np.float32), np.full(n, 77.0))


async def _setup():
    bus = EventBus(default_partitions=2)
    await bus.initialize()
    await bus.start()
    ep = KafkaEndpoint(bus)
    await ep.start()
    client = KafkaWireClient("127.0.0.1", ep.port)
    await client.connect()
    return bus, ep, client


def test_round_trips_and_offsets(run):
    async def main():
        bus, ep, client = await _setup()
        try:
            versions = await client.api_versions()
            # Produce v0..v1 served (v1 adds throttle_time_ms — the
            # flow-control quota surface; see tests/test_flow.py)
            assert (0, 0, 1) in versions

            # in-proc object -> Kafka fetch (codec bytes decode back)
            batch = _mk_batch()
            await bus.produce("t.events", batch, partition=0)
            err, hwm, msgs = await client.fetch("t.events", 0, 0)
            assert err == 0 and hwm == 1 and len(msgs) == 1
            obj = codec.decode(msgs[0][1])
            np.testing.assert_array_equal(obj.value, batch.value)

            # Kafka produce of codec bytes -> in-proc consumer gets the
            # OBJECT back (swx <-> swx over the wire is exact)
            err, base = await client.produce(
                "t.events", 0, [(b"k1", codec.encode(batch))])
            assert err == 0 and base == 1
            consumer = bus.subscribe("t.events", group="g1")
            got = []
            for _ in range(50):
                got += [r.value for r in
                        await consumer.poll(max_records=8, timeout=0.1)]
                if len(got) >= 2:
                    break
            assert isinstance(got[1], MeasurementBatch)
            consumer.commit()

            # foreign raw bytes pass through as bytes
            err, _ = await client.produce("t.events", 0,
                                          [(None, b"not-codec")])
            assert err == 0
            got2 = []
            for _ in range(50):
                got2 += [r.value for r in
                         await consumer.poll(max_records=8, timeout=0.1)]
                if got2:
                    break
            assert got2 == [b"not-codec"]

            # offsets: earliest 0, latest 3
            assert (await client.list_offsets("t.events", 0, -2))[1] == [0]
            assert (await client.list_offsets("t.events", 0, -1))[1] == [3]

            # group offsets are SHARED with the in-proc group
            consumer.commit()
            assert await client.offset_fetch("g1", "t.events", 0) == 3
            await client.offset_commit("g1", "t.events", 0, 3)
            assert bus._groups["g1"].committed[("t.events", 0)] == 3
            consumer.close()
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())


def test_trim_yields_offset_out_of_range(run):
    async def main():
        bus = EventBus(default_partitions=1, retention=4)
        await bus.initialize()
        await bus.start()
        ep = KafkaEndpoint(bus)
        await ep.start()
        client = KafkaWireClient("127.0.0.1", ep.port)
        await client.connect()
        try:
            for i in range(10):
                await bus.produce("t", f"v{i}", partition=0)
            err, hwm, _ = await client.fetch("t", 0, 0)
            assert err == 1                      # OFFSET_OUT_OF_RANGE
            err, offs = await client.list_offsets("t", 0, -2)
            assert offs == [6]                   # earliest after trim
            err, hwm, msgs = await client.fetch("t", 0, 6)
            assert err == 0 and len(msgs) == 4
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())


def test_long_poll_fetch(run):
    async def main():
        bus, ep, client = await _setup()
        try:
            async def later():
                await asyncio.sleep(0.2)
                await bus.produce("lp", "hello", partition=0)

            task = asyncio.get_running_loop().create_task(later())
            t0 = asyncio.get_event_loop().time()
            err, hwm, msgs = await client.fetch("lp", 0, 0,
                                                max_wait_ms=5000,
                                                min_bytes=1)
            took = asyncio.get_event_loop().time() - t0
            assert err == 0 and len(msgs) == 1 and took < 3.0
            assert codec.decode(msgs[0][1]) == "hello"
            await task
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())


def test_endpoint_survives_fuzz(run):
    async def main():
        bus, ep, client = await _setup()
        try:
            rng = np.random.default_rng(90)
            valid = (struct.pack(">hhi", 3, 0, 1) + _s("c")
                     + struct.pack(">i", 0))
            for i in range(300):
                r, w = await asyncio.open_connection("127.0.0.1", ep.port)
                if i % 3 == 0:
                    blob = bytes(rng.integers(0, 256,
                                              int(rng.integers(4, 64)),
                                              dtype=np.uint8))
                    w.write(struct.pack(">i", len(blob)) + blob)
                elif i % 3 == 1:
                    # size lies: huge / negative
                    w.write(struct.pack(">i", 1 << 30) + b"xxxx")
                else:
                    cut = int(rng.integers(1, len(valid)))
                    w.write(struct.pack(">i", len(valid)) + valid[:cut])
                try:
                    await asyncio.wait_for(w.drain(), 2.0)
                except (ConnectionError, asyncio.TimeoutError):
                    pass
                w.close()
            assert ep.malformed > 0
            # still serving: a fresh valid round trip works
            await bus.produce("alive", "yes", partition=0)
            c2 = KafkaWireClient("127.0.0.1", ep.port)
            await c2.connect()
            err, hwm, msgs = await c2.fetch("alive", 0, 0)
            assert err == 0 and codec.decode(msgs[0][1]) == "yes"
            await c2.close()
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())


def test_acks0_produce_sends_no_response(run):
    """Real brokers send NO Produce response when acks=0; an unsolicited
    frame would desync the client's request pipeline."""
    async def main():
        bus, ep, client = await _setup()
        try:
            mset = encode_message_set([(0, None, b"fire-and-forget", 0)])
            body = (struct.pack(">hi", 0, 5000) + struct.pack(">i", 1)
                    + _s("t0") + struct.pack(">i", 1)
                    + struct.pack(">i", 0) + _b(mset))
            client._corr += 1
            req = (struct.pack(">hhi", 0, 0, client._corr)
                   + _s("c") + body)
            client.writer.write(struct.pack(">i", len(req)) + req)
            await client.writer.drain()
            # the very next call must get ITS OWN correlation id back
            # (the _call helper asserts it) — no stray produce response
            err, offs = await client.list_offsets("t0", 0, -1)
            assert err == 0 and offs == [1]     # the record landed
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())


def test_compressed_message_set_rejected(run):
    """A compressed wrapper message would be stored as one opaque blob
    and fed to in-proc consumers as garbage — refused with
    CORRUPT_MESSAGE instead."""
    async def main():
        bus, ep, client = await _setup()
        try:
            # attributes byte = 1 (gzip) on a magic-1 message
            body = (struct.pack(">bb", 1, 1) + struct.pack(">q", 0)
                    + _b(None) + _b(b"gzipped-blob"))
            msg = struct.pack(">I", zlib.crc32(body)) + body
            mset = struct.pack(">qi", 0, len(msg)) + msg
            err, _ = await client.produce("tz", 0, [])  # warm topic
            pb = (struct.pack(">hi", 1, 5000) + struct.pack(">i", 1)
                  + _s("tz") + struct.pack(">i", 1)
                  + struct.pack(">i", 0) + _b(mset))
            mv = await client._call(0, pb)
            off = 4
            nlen = struct.unpack_from(">h", mv, off)[0]
            off += 2 + nlen + 4
            pid, err2, base = struct.unpack_from(">ihq", mv, off)
            assert err2 == 2                      # CORRUPT_MESSAGE
            assert bus._topics["tz"].partitions[0].end_offset == 0
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())


def test_stop_interrupts_long_poll(run):
    """stop() must not wait out a pending long-poll Fetch (up to 30 s):
    registered fetch waiters are woken so shutdown is prompt."""
    async def main():
        bus, ep, client = await _setup()
        try:
            poll = asyncio.get_running_loop().create_task(
                client.fetch("idle", 0, 0, max_wait_ms=30_000,
                             min_bytes=1))
            await asyncio.sleep(0.2)              # poll is parked
            t0 = asyncio.get_event_loop().time()
            await asyncio.wait_for(ep.stop(), 5)
            assert asyncio.get_event_loop().time() - t0 < 3.0
            poll.cancel()
            try:
                await poll
            except (asyncio.CancelledError, ConnectionError,
                    asyncio.IncompleteReadError):
                pass
        finally:
            await client.close()
            await bus.stop()

    run(main())


def test_hostile_codec_bytes_fall_back_to_raw(run):
    """The endpoint codec-decodes UNAUTHENTICATED foreign bytes; crafted
    payloads (truncated frames, huge claimed lengths, deep nesting,
    unregistered classes) must neither crash the endpoint nor allocate
    past the payload — they land as raw bytes."""
    async def main():
        bus, ep, client = await _setup()
        try:
            hostile = [
                codec.encode([1, 2, 3])[:5],            # truncated
                b"\x07" + (2**31 - 1).to_bytes(4, "big"),  # huge list len
                b"\x07\x00\x00\x00\x01" * 400,          # deep nesting
                b"\x0b" + b"\x00\x00\x00\x05Ghost"      # unregistered
                + b"\x00\x00\x00\x00",
            ]
            for i, payload in enumerate(hostile):
                err, _ = await client.produce("h", 0, [(None, payload)])
                assert err == 0, i
            consumer = bus.subscribe("h", group="hg")
            got = []
            for _ in range(50):
                got += [r.value for r in
                        await consumer.poll(max_records=8, timeout=0.1)]
                if len(got) == len(hostile):
                    break
            assert all(isinstance(v, bytes) for v in got), got
            assert got == hostile
            consumer.close()
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())


def test_protocol_edges(run):
    """v>0 negotiation (error 35 on ApiVersions; other APIs dropped),
    foreign->foreign byte fidelity (no codec prefix added), timestamp
    ListOffsets, and offset-0 commits sticking."""
    async def main():
        bus, ep, client = await _setup()
        try:
            # ApiVersions at v3 -> error 35 + the served list (the
            # standard fallback path; the client retries with v0)
            client._corr += 1
            req = (struct.pack(">hhi", 18, 3, client._corr)
                   + _s("c") + b"")
            client.writer.write(struct.pack(">i", len(req)) + req)
            await client.writer.drain()
            size = struct.unpack(">i",
                                 await client.reader.readexactly(4))[0]
            payload = await client.reader.readexactly(size)
            err = struct.unpack_from(">h", payload, 4)[0]
            assert err == 35

            # foreign bytes fetch back VERBATIM (no codec prefix)
            err, _ = await client.produce("ff", 0, [(None, b"raw-json")])
            assert err == 0
            err, hwm, msgs = await client.fetch("ff", 0, 0)
            assert msgs[0][1] == b"raw-json"

            # timestamp ListOffsets: first record at/after the point
            # (bus stamps wall-clock seconds at produce). Sleep on BOTH
            # sides of t_mid and round UP: int() truncation of a point
            # taken sub-ms after the first produce could land the query
            # at-or-before that record's timestamp (flaked 1-in-3 runs)
            import math
            import time as _time

            await asyncio.sleep(0.01)
            t_mid = math.ceil(_time.time() * 1000)
            await asyncio.sleep(0.01)
            await bus.produce("ff", "later", partition=0)
            err, offs = await client.list_offsets("ff", 0, t_mid)
            assert err == 0 and offs == [1]

            # max_num_offsets=0 -> empty offsets array, like a real
            # broker (the old [:max(n,1)] floor always returned one)
            err, offs = await client.list_offsets("ff", 0, -1, max_n=0)
            assert err == 0 and offs == []

            # offset-0 commit sticks (prev default must be -1, not 0)
            await client.offset_commit("gz", "ff", 0, 0)
            assert await client.offset_fetch("gz", "ff", 0) == 0
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())


def test_auto_create_topic_cap(run):
    """Unauthenticated peers can grow the topic map only up to the
    endpoint's auto-create cap; past it they get
    UNKNOWN_TOPIC_OR_PARTITION — while topics the in-proc services
    created are always served."""
    async def main():
        bus = EventBus(default_partitions=2)
        await bus.initialize()
        await bus.start()
        ep = KafkaEndpoint(bus, auto_create_limit=2)
        await ep.start()
        client = KafkaWireClient("127.0.0.1", ep.port)
        await client.connect()
        try:
            err, _ = await client.produce("cap-a", 0, [(None, b"x")])
            assert err == 0
            err, offs = await client.list_offsets("cap-b", 0, -1)
            assert err == 0
            # cap reached: produce/fetch/list_offsets all deny
            err, _ = await client.produce("cap-c", 0, [(None, b"x")])
            assert err == 3
            err, _hwm, _msgs = await client.fetch("cap-c", 0, 0)
            assert err == 3
            err, offs = await client.list_offsets("cap-c", 0, -1)
            assert err == 3 and offs == []
            # the denied topic never entered the bus map
            assert "cap-c" not in bus.topic_names()
            # service-created topics don't count against (or hit) the cap
            await bus.produce("svc-topic", b"y", partition=0)
            err, _hwm, msgs = await client.fetch("svc-topic", 0, 0)
            assert err == 0 and msgs[0][1] == b"y"
        finally:
            await client.close()
            await ep.stop()
            await bus.stop()

    run(main())
