"""Native host runtime (native/swx_native.cpp via persistence/native.py):
exact parity with the numpy fallback paths, duplicate handling, and the
GIL-released speed claim (smoke-level)."""

import os
import time

import numpy as np
import pytest

from sitewhere_tpu.persistence.native import get_lib
from sitewhere_tpu.persistence.telemetry import TelemetryTable

pytestmark = pytest.mark.skipif(
    get_lib() is None, reason="native library unavailable (no g++?)")


def _numpy_table(history, devices):
    """A table forced onto the numpy path for parity comparison."""
    t = TelemetryTable(history=history, initial_devices=devices)
    return t


def _run_append(table, dev, vals, ts, native: bool):
    if native:
        table.append(dev, vals, ts)
        return
    # numpy reference path, copied semantics (sort + cumcount)
    n = dev.shape[0]
    d = dev.astype(np.int64)
    order = np.argsort(d, kind="stable")
    sd = d[order]
    uniq, start, counts = np.unique(sd, return_index=True, return_counts=True)
    cum = np.arange(n, dtype=np.int64) - np.repeat(start, counts)
    pos = (table.cursor[sd] + cum) % table.history
    table.values[sd, pos] = vals[order]
    table.ts[sd, pos] = ts[order]
    table.cursor[uniq] = (table.cursor[uniq] + counts) % table.history
    table.count[uniq] = np.minimum(table.count[uniq] + counts, table.history)


def test_native_append_and_window_match_numpy():
    rng = np.random.default_rng(0)
    hist, ndev, w = 32, 64, 16
    nat = TelemetryTable(history=hist, initial_devices=ndev)
    ref = TelemetryTable(history=hist, initial_devices=ndev)
    for _ in range(7):
        n = int(rng.integers(1, 200))
        dev = rng.integers(0, ndev, n).astype(np.uint32)  # duplicates likely
        vals = rng.normal(size=n).astype(np.float32)
        ts = rng.uniform(1, 2, n)
        _run_append(nat, dev, vals, ts, native=True)
        _run_append(ref, dev, vals, ts, native=False)
        np.testing.assert_array_equal(nat.cursor, ref.cursor)
        np.testing.assert_array_equal(nat.count, ref.count)
        np.testing.assert_array_equal(nat.values, ref.values)
        np.testing.assert_array_equal(nat.ts, ref.ts)
    devices = np.arange(ndev, dtype=np.uint32)
    # window: native gather vs the numpy expression
    x_nat, v_nat = nat.window(devices, w)
    idx = (ref.cursor[devices, None] - w + np.arange(w)[None, :]) % hist
    x_ref = ref.values[devices[:, None], idx]
    v_ref = (np.arange(w)[None, :]
             >= (w - np.minimum(ref.count[devices], w)[:, None]))
    np.testing.assert_array_equal(x_nat, x_ref)
    np.testing.assert_array_equal(v_nat, v_ref)
    # window_ts + latest parity
    ts_nat = nat.window_ts(devices, w)
    ts_ref = ref.ts[devices[:, None], idx]
    np.testing.assert_array_equal(ts_nat, ts_ref)
    lv, lt = nat.latest(devices)
    li = (ref.cursor[devices.astype(np.int64)] - 1) % hist
    np.testing.assert_array_equal(lv, ref.values[devices, li])
    np.testing.assert_array_equal(lt, ref.ts[devices, li])


def test_native_append_in_batch_duplicate_order():
    t = TelemetryTable(history=8, initial_devices=4)
    dev = np.array([1, 1, 1, 2, 1], np.uint32)
    vals = np.arange(5, dtype=np.float32)
    t.append(dev, vals, np.ones(5))
    x, valid = t.window(np.array([1, 2], np.uint32), 4)
    assert list(x[0]) == [0.0, 1.0, 2.0, 4.0]  # device 1, arrival order
    assert valid[0].tolist() == [True] * 4
    assert x[1][-1] == 3.0 and valid[1].tolist() == [False, False, False, True]


def test_native_ring_wraparound():
    t = TelemetryTable(history=4, initial_devices=2)
    for k in range(10):
        t.append(np.array([0], np.uint32),
                 np.array([float(k)], np.float32), np.array([float(k)]))
    x, valid = t.window(np.array([0], np.uint32), 4)
    assert list(x[0]) == [6.0, 7.0, 8.0, 9.0]
    assert valid[0].all()


def test_native_speed_smoke():
    """Not a benchmark — just proof the native path isn't pathologically
    slow (it should beat numpy's sort+scatter comfortably)."""
    n, ndev = 16384, 16384
    t = TelemetryTable(history=256, initial_devices=ndev)
    dev = np.arange(n, dtype=np.uint32)
    vals = np.zeros(n, np.float32)
    ts = np.zeros(n)
    t.append(dev, vals, ts)  # warm
    t0 = time.perf_counter()
    for _ in range(10):
        t.append(dev, vals, ts)
    per_event = (time.perf_counter() - t0) / 10 / n
    assert per_event < 100e-9 * 50, f"native append too slow: {per_event*1e9:.0f} ns/event"
