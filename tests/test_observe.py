"""Pipeline flight recorder tests (kernel/observe.py + kernel/tracing.py).

Covers the ISSUE-9 acceptance surface: full-journey trace completeness
for a scored event on BOTH ingress lanes (≥7 spans receiver →
egress.publish, with the dispatch/settle split), consumer-lag gauges
under an induced backlog, the event-loop lag probe catching a
deliberately blocked loop within one beat (the PR-6 live-lock class),
observe-on/off output equivalence, the REST/`swx top` surfaces, and the
TRC01 lint contract.
"""

import asyncio
import contextlib
import time

import numpy as np

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.kernel.bus import EventBus, TopicRecord
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.kernel.tracing import Tracer
from sitewhere_tpu.services import (
    DeviceManagementService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
    RuleProcessingService,
)
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_pipeline import wait_until

DEVICES = 64

SPINE = {
    "event-sources.receive",
    "event-sources.decode",
    "inbound.enrich",
    "event-management.persist",
    "rule-processing.dispatch",
    "rule-processing.score",
    "egress.publish",
}


@contextlib.asynccontextmanager
async def observed_pipeline(observe: bool = True, fastlane: bool = True,
                            **rp_extra):
    """Full scored pipeline, every trace sampled (trace_sample=1)."""
    rt = ServiceRuntime(InstanceSettings(
        instance_id="obs", trace_sample=1, observe_enabled=observe,
        observe_interval_ms=50.0))
    for cls in (DeviceManagementService, EventSourcesService,
                InboundProcessingService, EventManagementService,
                DeviceStateService, RuleProcessingService):
        rt.add_service(cls(rt))
    await rt.start()
    sections = {
        "rule-processing": {"model": "zscore",
                            "model_config": {"window": 8},
                            "threshold": 6.0, "batch_window_ms": 1.0,
                            "buckets": [DEVICES], "capacity": DEVICES,
                            **rp_extra},
    }
    if not fastlane:
        sections["fastlane"] = {"enabled": False}
    await rt.add_tenant(TenantConfig(tenant_id="acme", sections=sections))
    dm = rt.api("device-management").management("acme")
    dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), DEVICES)
    eng = rt.api("rule-processing").engine("acme")
    sink = eng.session or eng.pool_slot
    await wait_until(lambda: sink.ready, timeout=60.0)
    try:
        yield rt
    finally:
        await rt.stop()


async def drive_scored(rt, ticks: int = 3) -> list:
    """Push payloads through the receiver; return the scored batches
    published on the scored-events topic (waits for them)."""
    consumer = rt.bus.subscribe(
        rt.naming.tenant_topic("acme", "scored-events"),
        group="test-observe-meter")
    sim = DeviceSimulator(SimConfig(num_devices=DEVICES), tenant_id="acme")
    receiver = rt.api("event-sources").engine("acme").receiver("default")
    for k in range(ticks):
        await receiver.submit(sim.payload(t=1000.0 + k)[0])
    scored: list = []
    expected = ticks * DEVICES

    async def drain():
        for r in consumer.poll_nowait(max_records=64):
            scored.append(r.value)
        return sum(len(s) for s in scored) >= expected

    deadline = asyncio.get_event_loop().time() + 30.0
    while not await drain():
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(
                f"scored {sum(len(s) for s in scored)}/{expected}")
        await asyncio.sleep(0.02)
    consumer.close()
    return scored


def _journey(rt, scored) -> list:
    """The full span journey of one scored batch's trace."""
    trace_id = scored[0].ctx.trace_id
    assert trace_id > 0
    return rt.tracer.trace(trace_id)


def test_full_journey_trace_fastlane(run):
    async def main():
        async with observed_pipeline(fastlane=True) as rt:
            eng = rt.api("rule-processing").engine("acme")
            assert eng.fastlane is not None  # the fused lane engaged
            scored = await drive_scored(rt)
            spans = _journey(rt, scored)
            stages = {s.stage for s in spans}
            # the acceptance bar: ≥7 spans spanning receiver →
            # egress.publish, including the dispatch/settle split
            assert SPINE <= stages, f"missing {SPINE - stages}"
            assert len(spans) >= 7
            ordered = [s.stage for s in spans]
            assert ordered[0] == "event-sources.receive"
            assert "egress.publish" in ordered
            # the split: dispatch (queue wait) precedes score (device)
            assert ordered.index("rule-processing.dispatch") \
                < ordered.index("rule-processing.score")

    run(main())


def test_full_journey_trace_staged_lane(run):
    async def main():
        async with observed_pipeline(fastlane=False) as rt:
            eng = rt.api("rule-processing").engine("acme")
            assert eng.fastlane is None  # staged slow lane pinned
            scored = await drive_scored(rt)
            stages = {s.stage for s in _journey(rt, scored)}
            assert SPINE <= stages, f"missing {SPINE - stages}"

    run(main())


def test_megabatch_dispatch_spans_attribute_tenant(run):
    async def main():
        async with observed_pipeline(megabatch={"enabled": True}) as rt:
            eng = rt.api("rule-processing").engine("acme")
            assert eng.pool_slot is not None  # pooled megabatch path
            scored = await drive_scored(rt)
            spans = _journey(rt, scored)
            stages = {s.stage for s in spans}
            assert SPINE <= stages, f"missing {SPINE - stages}"
            disp = [s for s in spans
                    if s.stage == "rule-processing.dispatch"]
            assert disp and all(s.tenant_id == "acme" for s in disp)

    run(main())


def test_consumer_lag_gauges_under_backlog(run):
    async def main():
        rt = ServiceRuntime(InstanceSettings(observe_interval_ms=50.0))
        await rt.start()
        consumer = rt.bus.subscribe("backlog-topic", group="lagging-group")
        for i in range(12):
            await rt.bus.produce("backlog-topic", i)
        sample = rt.beat.sample()
        assert sample["consumer_lag"]["lagging-group"] == 12
        assert sample["consumer_lag_max"] == 12
        assert rt.metrics.gauge("observe.consumer_lag").value == 12
        assert rt.metrics.gauge(
            "observe.consumer_lag:lagging-group").value == 12
        # consume 5, commit: lag drops to the uncommitted tail
        assert len(consumer.poll_nowait(max_records=5)) == 5
        consumer.commit()
        sample = rt.beat.sample()
        assert sample["consumer_lag"]["lagging-group"] == 7
        # a group whose consumers ALL died keeps reporting its backlog
        # (committed offsets alone carry the lag — the outage is when
        # the signal matters most)
        consumer.close()
        for i in range(3):
            await rt.bus.produce("backlog-topic", i)
        sample = rt.beat.sample()
        assert sample["consumer_lag"]["lagging-group"] == 10
        # drain + commit clears the lag on the next beat
        consumer2 = rt.bus.subscribe("backlog-topic",
                                     group="lagging-group")
        while consumer2.poll_nowait(max_records=64):
            pass
        consumer2.commit()
        sample = rt.beat.sample()
        assert sample["consumer_lag_max"] == 0
        consumer2.close()
        # a group that disappears has its per-suffix gauge ZEROED, not
        # left reporting its last value forever
        rt.metrics.gauge("observe.consumer_lag:lagging-group").set(7)
        del rt.bus._groups["lagging-group"]
        rt.beat.sample()
        assert rt.metrics.gauge(
            "observe.consumer_lag:lagging-group").value == 0
        await rt.stop()

    run(main())


def test_loop_lag_probe_catches_starved_loop(run):
    async def main():
        rt = ServiceRuntime(InstanceSettings(
            observe_interval_ms=50.0, observe_stall_ms=100.0))
        await rt.start()
        stalls0 = rt.metrics.counter("observe.loop_stalls").value
        await asyncio.sleep(0.12)  # beat cadence established
        # the synthetic PR-6 regression: a loop that stops yielding
        time.sleep(0.4)
        # within ONE beat of the loop resuming, the probe must flag it
        await asyncio.sleep(0.11)
        assert rt.metrics.counter("observe.loop_stalls").value > stalls0
        assert rt.metrics.histogram("observe.loop_lag_s")._max >= 0.25
        snap = rt.beat.snapshot()
        assert snap["loop_lag_ms"]["max"] >= 250.0
        await rt.stop()

    run(main())


def test_observe_on_off_output_equivalence(run):
    async def scores_with(observe: bool):
        async with observed_pipeline(observe=observe) as rt:
            assert (rt.beat is not None) == observe
            scored = await drive_scored(rt)
            pairs = np.concatenate([
                np.stack([b.device_index.astype(np.float64), b.score],
                         axis=1) for b in scored])
            return pairs[np.lexsort((pairs[:, 1], pairs[:, 0]))]

    async def main():
        on = await scores_with(True)
        off = await scores_with(False)
        np.testing.assert_allclose(on, off, rtol=1e-6)

    run(main())


def test_rest_observe_and_trace_pagination(run):
    from tests.test_rest import http

    async def main():
        from sitewhere_tpu.services import InstanceManagementService

        rt = ServiceRuntime(InstanceSettings(
            instance_id="obs-rest", rest_port=0, trace_sample=1,
            observe_interval_ms=50.0))
        for cls in (InstanceManagementService, DeviceManagementService,
                    EventSourcesService, InboundProcessingService,
                    EventManagementService, DeviceStateService,
                    RuleProcessingService):
            rt.add_service(cls(rt))
        await rt.start()
        port = rt.services["instance-management"].rest.port
        try:
            im = rt.services["instance-management"]
            await im.create_tenant("acme", "Acme", {
                "rule-processing": {"model": "zscore",
                                    "model_config": {"window": 8},
                                    "batch_window_ms": 1.0,
                                    "buckets": [DEVICES],
                                    "capacity": DEVICES}})
            dm = rt.api("device-management").management("acme")
            dm.bootstrap_fleet(DeviceType(token="thermo", name="T"),
                               DEVICES)
            eng = rt.api("rule-processing").engine("acme")
            await wait_until(lambda: eng.session.ready, timeout=60.0)
            scored = await drive_scored(rt)
            trace_id = scored[0].ctx.trace_id

            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            tok = body["token"]
            # the acceptance query: one scored event's full journey
            status, body = await http(
                port, "GET", f"/api/instance/traces/{trace_id}", token=tok)
            assert status == 200
            stages = {s["stage"] for s in body["spans"]}
            assert SPINE <= stages and len(body["spans"]) >= 7
            # tenant filtering: a bogus tenant filters everything out
            status, body = await http(
                port, "GET",
                f"/api/instance/traces/{trace_id}?tenant=nobody",
                token=tok)
            assert status == 200 and body["spans"] == []
            # pagination on the span listing
            status, page1 = await http(
                port, "GET", "/api/instance/traces/spans?limit=2",
                token=tok)
            status, page2 = await http(
                port, "GET",
                "/api/instance/traces/spans?limit=2&offset=2", token=tok)
            assert len(page1["spans"]) == 2 and len(page2["spans"]) == 2
            assert page1["spans"] != page2["spans"]
            # the observe report: critical path + beat
            status, rep = await http(port, "GET", "/api/instance/observe",
                                     token=tok)
            assert status == 200
            assert rep["beat"] is not None
            assert "rule-processing.score" in rep["critical_path"]["stages"]
            assert rep["critical_path"]["queue_wait_p99_ms"] >= 0
            # prometheus exposition carries the observe gauges
            status, _hdrs, text = await http(
                port, "GET", "/api/instance/metrics/prometheus",
                token=tok, raw=True)
            assert status == 200
            assert b"observe_loop_lag_s" in text
            # `swx top` renders the same report (the operator surface)
            from sitewhere_tpu.cli import render_top

            screen = render_top(rep)
            assert "rule-processing.score" in screen
            assert "critical path" in screen
        finally:
            await rt.stop()

    run(main())


def test_tracer_per_stage_rings_and_quantiles():
    tr = Tracer(sample=1, stage_capacity=8)
    # a chatty stage floods its ring ...
    for i in range(100):
        tr.record(i + 1, "egress.publish", "acme", float(i), 0.001, 1)
    # ... but can no longer evict another stage's spans
    tr.record(1, "event-sources.decode", "acme", 0.0, 0.010, 4)
    assert len(tr.spans(stage="event-sources.decode", limit=-1)) == 1
    summ = tr.stage_summary()
    assert summ["egress.publish"]["count"] == 8  # per-stage ring cap
    for key in ("p50_ms", "p95_ms", "p99_ms"):
        assert key in summ["egress.publish"]
    assert abs(summ["egress.publish"]["p50_ms"] - 1.0) < 0.5
    # tenant filter
    tr.record(2, "event-sources.decode", "other", 1.0, 0.010, 4)
    assert "event-sources.decode" not in tr.stage_summary(tenant="nobody")
    assert tr.stage_summary(tenant="other")[
        "event-sources.decode"]["count"] == 1
    # critical path classifies queue vs service and splits the p99
    tr.record(8, "rule-processing.dispatch", "acme", 0.0, 0.004, 1)
    cp = tr.critical_path()
    assert cp["stages"]["rule-processing.dispatch"]["kind"] == "queue"
    assert cp["stages"]["egress.publish"]["kind"] == "service"
    assert cp["queue_wait_p99_ms"] > 0 and cp["service_p99_ms"] > 0
    # pipeline order: dispatch renders before egress.publish
    keys = list(cp["stages"])
    assert keys.index("rule-processing.dispatch") \
        < keys.index("egress.publish")


def test_dlq_quarantine_and_replay_spans(run):
    from sitewhere_tpu.kernel.dlq import quarantine, replay_dead_letters

    async def main():
        bus = EventBus()
        tracer = Tracer(sample=1)
        ctx = BatchContext(tenant_id="acme", source="s", trace_id=7)
        batch = MeasurementBatch(
            ctx, np.asarray([1], np.uint32), np.asarray([0], np.uint16),
            np.asarray([1.0], np.float32), np.asarray([1.0], np.float64))
        record = TopicRecord("orig-topic", 0, 0, "k", batch, time.time())
        await quarantine(bus, "dlq-topic", record, ValueError("poison"),
                         "test.stage", tenant_id="acme", tracer=tracer)
        stages = {s.stage for s in tracer.trace(7)}
        assert "dlq.quarantine" in stages
        n = await replay_dead_letters(bus, "dlq-topic", tenant_id="acme",
                                      tracer=tracer)
        assert n == 1
        stages = {s.stage for s in tracer.trace(7)}
        assert "dlq.replay" in stages

    run(main())


def test_trc01_lint_contract():
    from sitewhere_tpu.analysis.checkers_trace import (
        check_trace_parity,
        check_trace_stages,
    )
    from sitewhere_tpu.analysis.engine import lint_sources

    # a hot-path hop that produces without a span is the regression
    bad = ("async def forward(self, record):\n"
           "    await self.bus.produce('t', record.value)\n")
    report = lint_sources({"sitewhere_tpu/kernel/fastlane.py": bad},
                          checkers=[check_trace_parity])
    assert [f.code for f in report.findings] == ["TRC01"]
    # recording a span on the same path satisfies the contract
    good = ("async def forward(self, record):\n"
            "    await self.bus.produce('t', record.value)\n"
            "    self.tracer.record(1, 'inbound.enrich', 't', 0.0, 0.0)\n")
    report = lint_sources({"sitewhere_tpu/kernel/fastlane.py": good},
                          checkers=[check_trace_parity])
    assert not report.findings
    # modules outside the contract are untouched
    report = lint_sources({"sitewhere_tpu/models/zscore.py": bad},
                          checkers=[check_trace_parity])
    assert not report.findings
    # stage literals resolve against the central inventory (any module)
    typo = ("def f(self):\n"
            "    self.tracer.record(1, 'rule-processing.scoer', 't',"
            " 0.0, 0.0)\n")
    report = lint_sources({"sitewhere_tpu/models/zscore.py": typo},
                          checkers=[check_trace_stages])
    assert [f.code for f in report.findings] == ["TRC01"]
    computed = ("def f(self, name):\n"
                "    self.tracer.record(1, name, 't', 0.0, 0.0)\n")
    report = lint_sources({"sitewhere_tpu/models/zscore.py": computed},
                          checkers=[check_trace_stages])
    assert [f.code for f in report.findings] == ["TRC01"]

    # the live tree satisfies the contract (new findings would also
    # fail test_analysis's package meta-test; assert here for locality)
    from sitewhere_tpu.analysis.engine import lint_package

    package = lint_package()
    assert not [f for f in package.findings if f.code == "TRC01"]


def test_deferred_spool_spans(run):
    async def main():
        async with observed_pipeline(fastlane=False) as rt:
            # any shed mode rejects NEW publishes at ingress, so feed
            # the scorer's consumer directly while defer is pinned (the
            # test_flow spool pattern): traffic already inside the
            # pipeline takes the flow.defer off-ramp
            enriched = rt.naming.tenant_topic("acme", "outbound-enriched-events")
            rt.flow.force_mode("acme", "defer")
            ctx = BatchContext(tenant_id="acme", source="direct",
                               trace_id=rt.tracer.new_trace_id())
            batch = MeasurementBatch(
                ctx, np.arange(8, dtype=np.uint32),
                np.zeros(8, np.uint16), np.ones(8, np.float32),
                np.full(8, 5000.0))
            await rt.bus.produce(enriched, batch)
            await wait_until(lambda: rt.tracer.spans(stage="flow.defer"),
                             timeout=20.0)
            defer_span = rt.tracer.spans(stage="flow.defer")[0]
            # overload clears → the spool drains back through the scorer
            rt.flow.force_mode("acme", "ok")
            await wait_until(
                lambda: rt.tracer.spans(stage="flow.replay"), timeout=20.0)
            replay = rt.tracer.spans(stage="flow.replay")[0]
            # same trace: the journey shows spool → replay
            assert replay.trace_id == defer_span.trace_id

    run(main())
