"""Cold-tier compaction + replay-plane tests (sitewhere_tpu/history).

Covers the PR-20 correctness contract: flush-split windows merge at
read; restart mid-compaction resumes idempotently (crash-before-
manifest leaves orphan bytes, never duplicate reads); a CRC/torn tail
is skipped LOUDLY and counted; double replay is byte-identical; replay
scores the exact records live scored (same records, same model version
-> identical scores); the shadow-scoring gate trips on a diverged
candidate and promotes an equivalent one; the version fence aborts a
replay when a hot-swap lands mid-range.
"""

import asyncio
import glob
import logging
import os
import shutil

import numpy as np
import pytest

from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.history import (DivergenceGateError, EventHistoryStore,
                                   ReplayEngine, ReplayFenceError,
                                   ScoreCollector)
from sitewhere_tpu.kernel.metrics import MetricsRegistry
from sitewhere_tpu.models.registry import build_model
from sitewhere_tpu.persistence.durable import RT_MEASUREMENTS, SegmentLog
from sitewhere_tpu.persistence.telemetry import TelemetryStore
from sitewhere_tpu.scoring.pool import PoolConfig, SharedScoringPool

T0 = 1_700_000_000.0
DEVICES = 32


def build_corpus(root, n_batches=8, per_batch=500, devices=DEVICES,
                 segment_bytes=1 << 14, seed=7):
    """Append `n_batches` measurement batches to a SegmentLog with a
    strictly increasing (hence globally unique) ts column — order and
    identity checks below lean on that. Small segments force several
    sealed files per corpus."""
    rng = np.random.default_rng(seed)
    log = SegmentLog(root, segment_bytes=segment_bytes)
    batches = []
    for i in range(n_batches):
        n = per_batch
        dev = rng.integers(0, devices, n).astype(np.uint32)
        base = T0 + i * per_batch * 0.01
        ts = (base + np.arange(n) * 0.01).astype(np.float64)
        val = rng.normal(20.0, 5.0, n).astype(np.float32)
        b = MeasurementBatch(BatchContext("acme"), dev,
                             np.zeros(n, np.uint16), val, ts)
        log.append(RT_MEASUREMENTS, b.encode())
        batches.append(b)
    log.close()
    return log, batches


def read_all(store):
    """Concatenate every window read_range yields, in yield order."""
    dev, val, ts = [], [], []
    for _, cols in store.read_range():
        dev.append(np.asarray(cols["device_index"]))
        val.append(np.asarray(cols["value"]))
        ts.append(np.asarray(cols["ts"]))
    if not dev:
        return (np.empty(0, np.uint32), np.empty(0, np.float32),
                np.empty(0, np.float64))
    return np.concatenate(dev), np.concatenate(val), np.concatenate(ts)


class TestCompaction:
    def test_flush_split_windows_merge_at_read(self, tmp_path):
        # one giant window + tiny block_events => many blocks, one
        # window; read_range must hand back a single merged column set
        # in log order
        log, batches = build_corpus(str(tmp_path / "events"))
        store = EventHistoryStore(str(tmp_path / "hist"), source=log,
                                  window_s=1e9, block_events=100)
        rep = store.compact(through_seq=log._seq)
        n = sum(len(b) for b in batches)
        assert rep["events"] == n
        st = store.stats()
        assert st["windows"] == 1 and st["blocks"] > 1
        windows = list(store.read_range())
        assert len(windows) == 1
        dev, val, ts = read_all(store)
        want_ts = np.concatenate([b.ts for b in batches])
        want_dev = np.concatenate([b.device_index for b in batches])
        assert ts.tobytes() == want_ts.tobytes()      # exact log order
        assert dev.tobytes() == want_dev.tobytes()

    def test_restart_mid_compaction_resumes_idempotently(self, tmp_path):
        log, batches = build_corpus(str(tmp_path / "events"))
        n = sum(len(b) for b in batches)
        seqs = [seq for seq, _ in log._segments()]
        assert len(seqs) > 2, "corpus must span several sealed segments"
        mid = seqs[len(seqs) // 2]

        store = EventHistoryStore(str(tmp_path / "hist"), source=log,
                                  window_s=30.0)
        rep1 = store.compact(through_seq=mid)
        assert 0 < rep1["events"] < n
        assert store.compacted_through_seq == mid

        # "restart": a fresh instance over the same directory resumes
        # from the manifest high-water mark, folding only the rest
        store2 = EventHistoryStore(str(tmp_path / "hist"), source=log,
                                   window_s=30.0)
        assert store2.compacted_through_seq == mid
        rep2 = store2.compact(through_seq=log._seq)
        assert rep1["events"] + rep2["events"] == n
        assert store2.stats()["events"] == n

        # idempotent: nothing left to fold, and a re-run adds no events
        rep3 = store2.compact(through_seq=log._seq)
        assert rep3 == {"segments": 0, "events": 0, "blocks": 0}
        assert store2.stats()["events"] == n

        # a window flush-split ACROSS the two passes still merges at
        # read, preserving log order end to end
        _, _, ts = read_all(store2)
        assert ts.shape[0] == n and bool((np.diff(ts) > 0).all())

    def test_crash_before_manifest_rewrite_never_duplicates(self, tmp_path):
        # crash model (store.py module docstring): a pass that died
        # after appending blocks but BEFORE the manifest rewrite leaves
        # unreferenced bytes in the block file. Simulate by restoring
        # the pre-pass manifest, then re-run: events read once, never
        # twice.
        log, batches = build_corpus(str(tmp_path / "events"))
        n = sum(len(b) for b in batches)
        seqs = [seq for seq, _ in log._segments()]
        mid = seqs[len(seqs) // 2]
        hist = tmp_path / "hist"
        store = EventHistoryStore(str(hist), source=log, window_s=30.0)
        store.compact(through_seq=mid)
        manifest = hist / "manifest.json"
        saved = manifest.read_bytes()

        store.compact(through_seq=log._seq)        # the pass that "crashes"
        manifest.write_bytes(saved)                # ...before its rewrite

        store2 = EventHistoryStore(str(hist), source=log, window_s=30.0)
        assert store2.compacted_through_seq == mid
        store2.compact(through_seq=log._seq)       # resume re-folds the rest
        assert store2.stats()["events"] == n
        _, _, ts = read_all(store2)
        assert ts.shape[0] == n and np.unique(ts).shape[0] == n

    def test_torn_tail_skipped_loudly_and_counted(self, tmp_path, caplog):
        log, batches = build_corpus(str(tmp_path / "events"))
        n = sum(len(b) for b in batches)
        segs = [p for p in sorted(glob.glob(str(tmp_path / "events" / "*")))
                if os.path.getsize(p) > 0]   # skip the empty active seg
        last = segs[-1]
        size = os.path.getsize(last)
        with open(last, "r+b") as f:       # tear the final record
            f.truncate(size - 7)
        store = EventHistoryStore(str(tmp_path / "hist"), source=log,
                                  window_s=30.0)
        with caplog.at_level(logging.WARNING,
                             logger="sitewhere_tpu.history.store"):
            rep = store.compact(through_seq=log._seq)
        assert rep["tail_skips"] >= 1
        assert store.stats()["tail_skips"] >= 1
        assert 0 < rep["events"] < n       # intact prefix kept, tail gone
        assert any("tail skipped" in r.message for r in caplog.records)
        # the count survives restart via the manifest
        store2 = EventHistoryStore(str(tmp_path / "hist"), source=log)
        assert store2.stats()["tail_skips"] >= 1

    def test_crc_corruption_skips_tail_loudly(self, tmp_path, caplog):
        log, batches = build_corpus(str(tmp_path / "events"))
        n = sum(len(b) for b in batches)
        segs = [p for p in sorted(glob.glob(str(tmp_path / "events" / "*")))
                if os.path.getsize(p) > 0]
        with open(segs[-1], "r+b") as f:
            # flip a byte INSIDE the first record's payload (past the
            # 9-byte len|crc|rtype header) => CRC mismatch, not torn-len
            f.seek(9 + 100)
            byte = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([byte[0] ^ 0xFF]))
        store = EventHistoryStore(str(tmp_path / "hist"), source=log,
                                  window_s=30.0)
        with caplog.at_level(logging.WARNING,
                             logger="sitewhere_tpu.history.store"):
            rep = store.compact(through_seq=log._seq)
        assert rep["tail_skips"] >= 1
        assert rep["events"] < n
        assert any("CRC mismatch" in r.message for r in caplog.records)


def make_pool(metrics, model_name="zscore", **model_kw):
    model = build_model(model_name, window=16, **model_kw)
    return SharedScoringPool(model, metrics,
                             PoolConfig(batch_buckets=(256, 2048),
                                        batch_window_ms=1.0))


class TestReplay:
    def _corpus_store(self, tmp_path, metrics=None):
        log, batches = build_corpus(str(tmp_path / "events"))
        store = EventHistoryStore(str(tmp_path / "hist"), source=log,
                                  window_s=30.0, metrics=metrics)
        store.compact(through_seq=log._seq)
        return log, batches, store

    def test_double_replay_byte_identical(self, run, tmp_path):
        metrics = MetricsRegistry()
        log, batches, store = self._corpus_store(tmp_path, metrics)
        n = sum(len(b) for b in batches)

        async def go():
            pool = make_pool(metrics)
            try:
                eng = ReplayEngine(pool, metrics=metrics)
                c1, c2 = ScoreCollector(), ScoreCollector()
                r1 = await eng.replay("acme", store, 6.0, collect=c1)
                r2 = await eng.replay("acme", store, 6.0, collect=c2)
                assert r1["events"] == r2["events"] == n
                assert r1["scored"] == r2["scored"] == n
                t1, t2 = c1.table(), c2.table()
                for a, b in zip(t1, t2):
                    assert a.tobytes() == b.tobytes()
            finally:
                pool.close()

        run(go())

    def test_replay_matches_live_scoring(self, run, tmp_path):
        # the acceptance pin: the same records through the LIVE admit
        # path and through compaction+replay produce identical scored
        # output — same model version, byte-identical score table
        metrics = MetricsRegistry()
        log, batches, store = self._corpus_store(tmp_path)
        n = sum(len(b) for b in batches)

        async def go():
            pool = make_pool(metrics)
            try:
                live = ScoreCollector()
                slot = pool.register("acme", TelemetryStore(), 6.0, live)
                for b in batches:
                    slot.admit(b)
                    while not slot.idle:
                        slot.flush_nowait()
                        await asyncio.sleep(0.002)
                pool.unregister("acme")

                eng = ReplayEngine(pool, metrics=metrics)
                rep = ScoreCollector()
                r = await eng.replay("acme", store, 6.0, collect=rep)
                assert r["events"] == n
                lt, rt = live.table(), rep.table()
                assert live.versions == rep.versions
                for a, b in zip(lt, rt):
                    assert a.tobytes() == b.tobytes()
                assert lt[0].shape[0] == n
            finally:
                pool.close()

        run(go())

    def test_fence_aborts_on_midreplay_swap(self, run, tmp_path):
        metrics = MetricsRegistry()
        log, batches, store = self._corpus_store(tmp_path)
        assert len(store.windows()) >= 2

        class SwapAfterFirstWindow:
            """read_range shim that lands a hot-swap between windows —
            deterministically mid-replay."""

            def __init__(self, inner, slot):
                self.inner, self.slot = inner, slot

            def read_range(self, since=None, until=None):
                for i, item in enumerate(self.inner.read_range(since,
                                                               until)):
                    yield item
                    if i == 0:
                        self.slot.swap_params(
                            self.slot.pool.stack.get_params("acme"))

        async def sink(scored):
            pass

        async def go():
            pool = make_pool(metrics)
            try:
                slot = pool.register("acme", TelemetryStore(), 6.0, sink)
                eng = ReplayEngine(pool, metrics=metrics)
                shim = SwapAfterFirstWindow(store, slot)
                with pytest.raises(ReplayFenceError):
                    await eng.replay("acme", shim, 6.0, fence=slot)
                # the transient replay slot must not leak on abort
                assert all(not t.startswith("tenant-0.replay:")
                           for t in pool.tenants)
            finally:
                pool.close()

        run(go())

    def test_divergence_gate_trips_and_promotes(self, run, tmp_path):
        import jax

        metrics = MetricsRegistry()
        log, batches, store = self._corpus_store(tmp_path)

        async def sink(scored):
            pass

        async def go():
            # zscore is stateless-params — the gate needs a parametric
            # model to have anything to diverge
            pool = make_pool(metrics, "lstm", hidden=8)
            try:
                eng = ReplayEngine(pool, metrics=metrics)
                slot = pool.register("acme", TelemetryStore(), 6.0, sink)
                live = pool.stack.get_params("acme")
                bad = jax.tree.map(lambda a: a + 0.5, live)
                v0 = slot.version
                with pytest.raises(DivergenceGateError) as ei:
                    await eng.guard_swap(slot, store, bad,
                                         max_divergence=0.05)
                assert ei.value.report["max_abs"] > 0.05
                assert ei.value.report["promoted"] is False
                assert slot.version == v0          # refused => no swap
                snap = metrics.snapshot()
                assert snap["history.divergence_max"] > 0.05

                # an equivalent candidate sails through and promotes
                v, rep = await eng.guard_swap(slot, store, live,
                                              max_divergence=0.05)
                assert rep["promoted"] and rep["max_abs"] == 0.0
                assert v == slot.version > v0
            finally:
                pool.close()

        run(go())

    def test_metrics_and_counters(self, run, tmp_path):
        metrics = MetricsRegistry()
        log, batches, store = self._corpus_store(tmp_path, metrics)
        n = sum(len(b) for b in batches)

        async def go():
            pool = make_pool(metrics)
            try:
                eng = ReplayEngine(pool, metrics=metrics)
                await eng.replay("acme", store, 6.0)
            finally:
                pool.close()

        run(go())
        snap = metrics.snapshot()
        assert snap["history.compactions"] >= 1
        assert snap["history.replay_events"] == n
        assert snap["history.replay_rate"] > 0
