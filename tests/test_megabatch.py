"""Cross-tenant megabatched scoring (scoring/pool.py): ISSUE 8's
acceptance tests.

- wiring/config: `rule-processing: {megabatch: {enabled}}` routes every
  tenant of one architecture through ONE shared stacked-params pool
  (dedicated sessions stay the default), with the configured megabatch
  window and tenants-per-dispatch bound.
- on/off equivalence: megabatch-on vs megabatch-off runs of the same
  event sequence produce identical per-tenant scores, persisted
  telemetry, alerts, and committed offsets — megabatching changes the
  dispatch count, never behavior — AND the on-leg's flush-path jit
  dispatch count collapses.
- version fence: a param hot-swap landing while a megabatch is in
  flight attributes that batch to the weights that scored it (the
  version snapshotted at dispatch), never the fresher one.
- lifecycle under load: tenant register (stack growth + rebuild
  accounting) and unregister (pending accounted as dropped) while other
  tenants keep scoring.
- `max_tenants` bounds tenants packed per stacked dispatch; leftovers
  flush the next round, nothing is lost.
- chaos: `scoring.megabatch` faults quarantine the admitting record to
  the tenant DLQ with provenance; later records score normally.
"""

import asyncio
import contextlib

import numpy as np

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.kernel.bus import TopicNaming
from sitewhere_tpu.kernel.metrics import MetricsRegistry
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.models import build_model
from sitewhere_tpu.persistence.telemetry import TelemetryStore
from sitewhere_tpu.scoring.pool import PoolConfig, SharedScoringPool
from sitewhere_tpu.services import (
    DeviceManagementService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
    RuleProcessingService,
)
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig
from tests.test_pipeline import wait_until

RULE = {"model": "zscore", "model_config": {"window": 16},
        "threshold": 6.0, "batch_window_ms": 1.0,
        "buckets": [256], "capacity": 256}

TENANTS = ("t0", "t1", "t2", "t3")


@contextlib.asynccontextmanager
async def megabatch_runtime(tenants=TENANTS, megabatch=True,
                            num_devices=32, faults=None,
                            instance_id="mb", rule_extra=None):
    """Full pipeline with N tenants, each `megabatch: {enabled}` pinned
    (True = the shared stacked-dispatch pool, False = dedicated
    per-tenant sessions — the A/B legs)."""
    rt = ServiceRuntime(InstanceSettings(instance_id=instance_id))
    for cls in (DeviceManagementService, EventSourcesService,
                InboundProcessingService, EventManagementService,
                DeviceStateService, RuleProcessingService):
        rt.add_service(cls(rt))
    if faults is not None:
        rt.install_faults(faults)
    await rt.start()
    for tid in tenants:
        rule = {**RULE, "megabatch": {"enabled": megabatch},
                **(rule_extra or {})}
        await rt.add_tenant(TenantConfig(tenant_id=tid,
                                         sections={"rule-processing": rule}))
        dm = rt.api("device-management").management(tid)
        dm.bootstrap_fleet(DeviceType(token="thermo", name="T"),
                           num_devices)
    for tid in tenants:
        eng = rt.api("rule-processing").engine(tid)
        sink = eng.session or eng.pool_slot
        await wait_until(lambda s=sink: s.ready, timeout=60.0)
    try:
        yield rt
    finally:
        await rt.stop()


async def _drive_tenants(rt, tenants=TENANTS, n_dev=32, ticks=10,
                         anomaly_rate=0.05):
    """Feed every tenant the same per-tenant-seeded sequence; return
    {tenant: (scored map, telemetry total, alert set, committed)} once
    everything drained and committed — the observables the on/off legs
    must agree on."""
    consumers = {tid: rt.bus.subscribe(
        rt.naming.tenant_topic(tid, TopicNaming.SCORED_EVENTS),
        group="mb-test-meter") for tid in tenants}
    sims = {tid: DeviceSimulator(
        SimConfig(num_devices=n_dev, seed=100 + i,
                  anomaly_rate=anomaly_rate, anomaly_magnitude=15.0),
        tenant_id=tid) for i, tid in enumerate(tenants)}
    receivers = {tid: rt.api("event-sources").engine(tid)
                 .receiver("default") for tid in tenants}
    for k in range(ticks):
        for tid in tenants:
            payload, _ = sims[tid].payload(t=1000.0 + 60.0 * k)
            assert await receivers[tid].submit(payload)
    expected = n_dev * ticks
    out = {}
    for tid in tenants:
        em = rt.api("event-management").management(tid)
        await wait_until(
            lambda em=em: em.telemetry.total_events >= expected,
            timeout=30.0)
        scored: dict = {}

        def collect(c=consumers[tid], scored=scored):
            for r in c.poll_nowait(max_records=512):
                b = r.value
                for i in range(len(b)):
                    scored[(int(b.device_index[i]), float(b.ts[i]))] = (
                        round(float(b.score[i]), 3),
                        bool(b.is_anomaly[i]))
            return len(scored) >= expected

        await wait_until(collect, timeout=30.0)
        consumers[tid].close()
        dm = rt.api("device-management").management(tid)
        alerts = {(dm.get_device(a.device_id).token, float(a.event_date),
                   a.type) for a in em.spi.alerts}
        decoded = rt.naming.tenant_topic(
            tid, TopicNaming.EVENT_SOURCE_DECODED)
        end_total = sum(rt.bus.end_offsets(decoded))
        group = rt.bus._groups[f"{tid}.inbound-processing"]

        def committed_total(group=group, decoded=decoded):
            return sum(off for (topic, _p), off in group.committed.items()
                       if topic == decoded)

        await wait_until(
            lambda c=committed_total, e=end_total: c() >= e, timeout=30.0)
        out[tid] = (scored, em.telemetry.total_events, alerts,
                    committed_total())
    return out


# -- wiring / config --------------------------------------------------------

def test_megabatch_wiring_and_config(run):
    async def main():
        async with megabatch_runtime(instance_id="mb-w") as rt:
            rp = rt.api("rule-processing")
            engines = [rp.engine(t) for t in TENANTS]
            # every tenant rides the pool, no dedicated sessions
            assert all(e.session is None for e in engines)
            pool = engines[0].pool_slot.pool
            assert all(e.pool_slot.pool is pool for e in engines)
            assert set(pool.stack.slots) == set(TENANTS)
            # megabatch window: instance default 1.0 ms (≤1 ms of
            # batching latency for the dispatch collapse)
            assert pool.cfg.window_s == 0.001
            # pool inflight bound plumbed from the tenant config
            assert pool.cfg.max_inflight == 64
        # tenant override beats the instance default
        async with megabatch_runtime(
                tenants=("solo",), instance_id="mb-wo",
                rule_extra={"megabatch": {"enabled": True,
                                          "window_ms": 4.0,
                                          "max_tenants": 2}}) as rt:
            pool = rt.api("rule-processing").engine("solo").pool_slot.pool
            assert pool.cfg.window_s == 0.004
            assert pool.cfg.max_tenants == 2
        # megabatch off → dedicated sessions (the default path)
        async with megabatch_runtime(megabatch=False,
                                     instance_id="mb-wn") as rt:
            engines = [rt.api("rule-processing").engine(t) for t in TENANTS]
            assert all(e.session is not None and e.pool_slot is None
                       for e in engines)

    run(main())


# -- equivalence + the dispatch collapse ------------------------------------

def test_megabatch_on_off_equivalence_and_dispatch_collapse(run):
    """The acceptance pair: identical per-tenant observables, collapsed
    jit dispatch count."""
    async def main():
        async with megabatch_runtime(megabatch=True,
                                     instance_id="mb-on") as rt:
            on = await _drive_tenants(rt)
            on_disp = rt.metrics.counter("scoring.dispatches").value
            on_mb = rt.metrics.counter("scoring.megabatch_dispatches").value
            on_tpd = rt.metrics.histogram(
                "scoring.megabatch_tenants_per_dispatch")
            # stacked dispatches happened, and they aggregated tenants
            assert on_mb > 0 and on_mb == on_disp
            assert on_tpd._max > 1.0
        async with megabatch_runtime(megabatch=False,
                                     instance_id="mb-off") as rt:
            off = await _drive_tenants(rt)
            off_disp = rt.metrics.counter("scoring.dispatches").value
            assert rt.metrics.counter(
                "scoring.megabatch_dispatches").value == 0
        for tid in TENANTS:
            scored_on, total_on, alerts_on, committed_on = on[tid]
            scored_off, total_off, alerts_off, committed_off = off[tid]
            assert total_on == total_off == 32 * 10
            assert scored_on.keys() == scored_off.keys()
            for key, val in scored_on.items():
                assert scored_off[key] == val, (tid, key)
            assert alerts_on == alerts_off and alerts_on
            assert committed_on == committed_off > 0
        # the point of the exercise: four tenants' flush rounds fused
        # into stacked dispatches — at 4 tenants the per-round ideal is
        # 4×; scheduling jitter may split rounds, so assert ≥2×
        assert on_disp * 2 <= off_disp, (on_disp, off_disp)

    run(main())


# -- version fence ----------------------------------------------------------

def _batch(tid: str, n: int = 8, t: float = 10.0,
           value: float = 21.0) -> MeasurementBatch:
    return MeasurementBatch(
        BatchContext(tenant_id=tid, source="test"),
        np.arange(n, dtype=np.uint32), np.zeros(n, np.uint16),
        np.full(n, value, np.float32), np.full(n, t))


def test_param_hot_swap_version_fence(run):
    """A swap landing after dispatch but before settle must not steal
    the in-flight megabatch's attribution: the settled batch carries
    the version snapshotted at dispatch."""
    async def main():
        model = build_model("lstm", window=16, hidden=8)
        pool = SharedScoringPool(
            model, MetricsRegistry(),
            PoolConfig(batch_buckets=(32,), batch_window_ms=50.0))
        delivered: list = []

        async def deliver(scored):
            delivered.append(scored)

        slot = pool.register("a", TelemetryStore(history=32), 6.0, deliver)
        await wait_until(lambda: pool.ready, timeout=60.0)
        fence0 = pool.stack.fence
        # admit + dispatch in ONE loop step (no awaits), so the
        # background flusher cannot race this round
        slot.admit(_batch("a"))
        pool._flush_round()
        # the swap lands while the dispatch is in flight (its settle
        # task exists but has not run yet)
        new_version = slot.swap_params(
            model.init(__import__("jax").random.PRNGKey(7)))
        assert new_version == 1
        assert pool.stack.fence > fence0
        await wait_until(lambda: len(delivered) == 1, timeout=30.0)
        # fence holds: attribution is the DISPATCH-time version
        assert delivered[0].model_version == 0
        # post-swap dispatches attribute to the new weights
        slot.admit(_batch("a", t=11.0))
        pool._flush_round()
        await wait_until(lambda: len(delivered) == 2, timeout=30.0)
        assert delivered[1].model_version == 1
        pool.close()

    run(main())


# -- tenant add/remove under load -------------------------------------------

def test_tenant_add_remove_under_load(run):
    async def main():
        metrics = MetricsRegistry()
        model = build_model("zscore", window=16)
        pool = SharedScoringPool(
            model, metrics, PoolConfig(batch_buckets=(32,),
                                       batch_window_ms=0.5))
        got: dict[str, int] = {}

        def deliver_for(tid):
            async def deliver(scored):
                got[tid] = got.get(tid, 0) + len(scored)
            return deliver

        for tid in ("a", "b"):
            pool.register(tid, TelemetryStore(history=32), 6.0,
                          deliver_for(tid))
        await wait_until(lambda: pool.ready, timeout=60.0)
        for tid in ("a", "b"):
            pool.admit(tid, _batch(tid))
        pool._flush_round()  # in flight for a+b
        # register c mid-flight: stack grows 2 → 4 (a rebuild), the
        # in-flight settle still lands
        pool.register("c", TelemetryStore(history=32), 6.0,
                      deliver_for("c"))
        assert pool.stack.capacity == 4
        assert metrics.counter("scoring.stack_rebuilds").value >= 1
        assert pool.stack.occupancy().sum() == 3
        await wait_until(lambda: got.get("a") == 8 and got.get("b") == 8,
                         timeout=30.0)
        await wait_until(lambda: pool.ready, timeout=60.0)
        # unregister b WITH pending: its events are accounted dropped,
        # the others keep scoring
        pool.admit("b", _batch("b", t=20.0))
        pending_b = pool.tenants["b"].pending_n
        assert pending_b == 8
        pool.unregister("b")
        assert metrics.counter(
            "scoring.admissions_dropped").value >= pending_b
        assert pool.stack.occupancy().sum() == 2
        for tid in ("a", "c"):
            pool.admit(tid, _batch(tid, t=21.0))
        pool._flush_round()
        await wait_until(lambda: got.get("a") == 16 and got.get("c") == 8,
                         timeout=30.0)
        assert "b" not in pool.stack.slots
        pool.close()

    run(main())


# -- max_tenants bound ------------------------------------------------------

def test_max_tenants_bounds_each_dispatch(run):
    async def main():
        metrics = MetricsRegistry()
        model = build_model("zscore", window=16)
        pool = SharedScoringPool(
            model, metrics, PoolConfig(batch_buckets=(32,),
                                       batch_window_ms=50.0,
                                       max_tenants=2))
        got: dict[str, int] = {}

        def deliver_for(tid):
            async def deliver(scored):
                got[tid] = got.get(tid, 0) + len(scored)
            return deliver

        tids = ("a", "b", "c", "d")
        for tid in tids:
            pool.register(tid, TelemetryStore(history=32), 6.0,
                          deliver_for(tid))
        await wait_until(lambda: pool.ready, timeout=60.0)
        for tid in tids:
            pool.admit(tid, _batch(tid))
        pool._flush_round()   # packs 2 tenants, re-arms the wake
        pool._flush_round()   # the other 2
        assert pool.megabatch_tenants._max <= 2.0
        await wait_until(lambda: all(got.get(t) == 8 for t in tids),
                         timeout=30.0)
        assert pool._total_pending == 0
        pool.close()

    run(main())


# -- chaos ------------------------------------------------------------------

def test_megabatch_chaos_quarantines_with_provenance(run):
    """An injected `scoring.megabatch` fault at admission dead-letters
    the admitting record with provenance; the pool (and its flusher)
    survive, and later records score normally."""
    async def main():
        from sitewhere_tpu.kernel.dlq import list_dead_letters
        from sitewhere_tpu.kernel.faults import FaultInjector

        fi = FaultInjector(seed=5)
        async with megabatch_runtime(tenants=("t0",), faults=fi,
                                     instance_id="mb-ch") as rt:
            fi.arm("scoring.megabatch", rate=1.0, max_faults=1)
            decoded = rt.naming.tenant_topic(
                "t0", TopicNaming.EVENT_SOURCE_DECODED)
            dlq = rt.naming.tenant_topic("t0", TopicNaming.DEAD_LETTER)
            scored_topic = rt.naming.tenant_topic(
                "t0", TopicNaming.SCORED_EVENTS)
            await rt.bus.produce(decoded, _batch("t0", n=16, t=1000.0),
                                 key="gw")
            await wait_until(
                lambda: len(list_dead_letters(rt.bus, dlq)) >= 1,
                timeout=15.0)
            entries = list_dead_letters(rt.bus, dlq)
            assert len(entries) == 1
            # quarantined by the admitting consumer lane (fused fast
            # lane or staged rule processor), with its provenance
            assert any(s in entries[0][1]["stage"]
                       for s in ("fastlane", "rule-processor"))
            assert entries[0][1]["original_topic"] == decoded
            # the fault is spent: later records admit + score normally
            consumer = rt.bus.subscribe(scored_topic, group="mb-ch-meter")
            await rt.bus.produce(decoded, _batch("t0", n=16, t=1060.0),
                                 key="gw")
            seen = []

            def collect():
                seen.extend(consumer.poll_nowait(max_records=64))
                return sum(len(r.value) for r in seen) >= 16
            await wait_until(collect, timeout=15.0)
            consumer.close()

    run(main())


# -- settle-task retention (swx lint TSK01 regression) -----------------------


def test_settle_task_retained_until_delivery(run):
    """The in-flight settle task is strongly referenced: the event loop
    keeps only a weak ref, so the pre-fix dropped handle could be GC'd
    mid-flight — wedging `inflight`/`_outstanding` forever with the
    megabatch never settling."""
    async def main():
        model = build_model("zscore", window=16)
        pool = SharedScoringPool(
            model, MetricsRegistry(),
            PoolConfig(batch_buckets=(32,), batch_window_ms=50.0))
        delivered: list = []

        async def deliver(scored):
            delivered.append(scored)

        slot = pool.register("a", TelemetryStore(history=32), 6.0, deliver)
        await wait_until(lambda: pool.ready, timeout=60.0)
        slot.admit(_batch("a"))
        pool._flush_round()
        assert len(pool._settle_tasks) == 1  # strong ref while in flight
        await wait_until(lambda: len(delivered) == 1, timeout=30.0)
        await wait_until(lambda: not pool._settle_tasks, timeout=5.0)
        pool.close()

    run(main())


def test_settle_task_failure_is_logged(run, caplog):
    """An escaped settle exception is retrieved and surfaced by the
    supervisor callback instead of dying unretrieved."""
    import logging

    async def main():
        pool = SharedScoringPool.__new__(SharedScoringPool)
        pool._settle_tasks = set()

        async def boom():
            raise RuntimeError("settle exploded")

        task = asyncio.get_running_loop().create_task(boom())
        pool._settle_tasks.add(task)
        task.add_done_callback(pool._settle_task_done)
        while pool._settle_tasks:
            await asyncio.sleep(0)

    with caplog.at_level(logging.ERROR, logger="sitewhere_tpu.scoring.pool"):
        run(main())
    assert any("settle task died" in r.getMessage() for r in caplog.records)
