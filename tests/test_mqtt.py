"""MQTT 3.1.1 ingest endpoint (services/mqtt.py): a hand-rolled client
speaks the real wire protocol — CONNECT/PUBLISH/SUBSCRIBE/PING — and the
full pipeline ingests its telemetry; command delivery rides the same
session [SURVEY.md §2.2 event-sources MQTT, command-delivery MQTT]."""

import asyncio

import numpy as np
import pytest

from sitewhere_tpu.services.mqtt import _encode_varint
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_pipeline import running_pipeline, wait_until


def _utf8(s: str) -> bytes:
    b = s.encode()
    return len(b).to_bytes(2, "big") + b


def _pkt(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + _encode_varint(len(body)) + body


def connect_pkt(client_id: str) -> bytes:
    body = _utf8("MQTT") + bytes([4, 2]) + (60).to_bytes(2, "big") \
        + _utf8(client_id)
    return _pkt(1, 0, body)


def publish_pkt(topic: str, payload: bytes, qos: int = 0,
                packet_id: int = 1) -> bytes:
    body = _utf8(topic)
    if qos:
        body += packet_id.to_bytes(2, "big")
    return _pkt(3, qos << 1, body + payload)


def subscribe_pkt(topic: str, packet_id: int = 7) -> bytes:
    return _pkt(8, 2, packet_id.to_bytes(2, "big") + _utf8(topic) + b"\x00")


async def read_pkt(reader) -> tuple[int, int, bytes]:
    (h,) = await reader.readexactly(1)
    mult, length = 1, 0
    while True:
        (b,) = await reader.readexactly(1)
        length += (b & 0x7F) * mult
        if not b & 0x80:
            break
        mult *= 128
    body = await reader.readexactly(length) if length else b""
    return h >> 4, h & 0x0F, body


def test_mqtt_ingest_and_command_roundtrip(run):
    async def main():
        from sitewhere_tpu.domain.events import DeviceCommandInvocation
        from sitewhere_tpu.domain.model import DeviceCommand
        from sitewhere_tpu.services import CommandDeliveryService

        sections = {
            "event-sources": {"receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"}]},
            "rule-processing": {"model": None},
            "command-delivery": {"provider": "mqtt", "encoder": "json"},
        }
        async with running_pipeline(num_devices=20, sections=sections,
                                    extra_services=(CommandDeliveryService,)) \
                as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            port = receiver.port
            reader, writer = await asyncio.open_connection("127.0.0.1", port)

            # CONNECT → CONNACK accepted
            writer.write(connect_pkt("dev-7"))
            await writer.drain()
            ptype, _, body = await read_pkt(reader)
            assert ptype == 2 and body[1] == 0

            # SUBSCRIBE to this device's command topic → SUBACK
            dm = rt.api("device-management").management("acme")
            device = dm.get_device_by_token("dev-7")
            writer.write(subscribe_pkt("swx/commands/dev-7"))
            await writer.drain()
            ptype, _, body = await read_pkt(reader)
            assert ptype == 9

            # PUBLISH telemetry (QoS1) → PUBACK + pipeline ingest
            sim = DeviceSimulator(SimConfig(num_devices=20), tenant_id="acme")
            for k in range(3):
                payload, _ = sim.payload(t=60.0 * k)
                writer.write(publish_pkt("swx/telemetry", payload, qos=1,
                                         packet_id=10 + k))
                await writer.drain()
                ptype, _, body = await read_pkt(reader)
                assert ptype == 4  # PUBACK

            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 60)

            # command invocation routes back down the SAME mqtt session
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="reboot", device_type_id=dt.id, name="reboot"))
            assignment = dm.get_active_assignments_for_device(device.id)[0]
            inv = DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id, parameter_values={"delay": 1})
            await em.add_command_invocations([inv])
            ptype, flags, body = await read_pkt(reader)
            assert ptype == 3  # PUBLISH down to the device
            tlen = int.from_bytes(body[:2], "big")
            topic = body[2:2 + tlen].decode()
            assert topic == "swx/commands/dev-7"
            assert b"reboot" in body[2 + tlen:]

            # PINGREQ → PINGRESP keeps the session alive
            writer.write(_pkt(12, 0, b""))
            await writer.drain()
            ptype, _, _ = await read_pkt(reader)
            assert ptype == 13
            writer.close()

    run(main())


def test_mqtt_rejects_garbage_and_survives(run):
    async def main():
        sections = {"event-sources": {"receivers": [
            {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"}]},
            "rule-processing": {"model": None}}
        async with running_pipeline(num_devices=5, sections=sections) as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            # a client that speaks garbage gets dropped without killing
            # the listener
            r1, w1 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w1.write(b"\xff\xff\xff\xff\xff\xff")
            await w1.drain()
            # a well-behaved client still connects fine afterwards
            r2, w2 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w2.write(connect_pkt("ok"))
            await w2.drain()
            ptype, _, body = await read_pkt(r2)
            assert ptype == 2 and body[1] == 0
            # garbage PUBLISH payload counts a decode failure, not a crash
            w2.write(publish_pkt("t", b"not swb1"))
            await w2.drain()
            await wait_until(lambda: rt.metrics.snapshot()
                             ["event_sources.decode_failures"] >= 1)
            w1.close()
            w2.close()

    run(main())


def connect_pkt_auth(client_id: str, username: str, password: str) -> bytes:
    flags = 0x02 | 0x80 | 0x40  # clean session + username + password
    body = _utf8("MQTT") + bytes([4, flags]) + (60).to_bytes(2, "big") \
        + _utf8(client_id) + _utf8(username) + _utf8(password)
    return _pkt(1, 0, body)


def test_mqtt_connect_requires_credentials_when_configured(run):
    """ADVICE regression: with `users` configured, an unauthenticated
    CONNECT is refused (code 4) and its PUBLISHes never reach the
    pipeline; correct credentials are accepted."""

    async def main():
        sections = {"event-sources": {"receivers": [
            {"kind": "mqtt", "decoder": "swb1", "name": "mqtt",
             "users": {"gateway": "s3cret"}}]},
            "rule-processing": {"model": None}}
        async with running_pipeline(num_devices=5, sections=sections) as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            # no credentials → refused
            r1, w1 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w1.write(connect_pkt("dev-1"))
            await w1.drain()
            ptype, _, body = await read_pkt(r1)
            assert ptype == 2 and body[1] == 4  # bad user or password
            # wrong password → refused
            r2, w2 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w2.write(connect_pkt_auth("dev-1", "gateway", "wrong"))
            await w2.drain()
            ptype, _, body = await read_pkt(r2)
            assert ptype == 2 and body[1] == 4
            # right credentials → accepted, telemetry flows
            r3, w3 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w3.write(connect_pkt_auth("dev-1", "gateway", "s3cret"))
            await w3.drain()
            ptype, _, body = await read_pkt(r3)
            assert ptype == 2 and body[1] == 0
            sim = DeviceSimulator(SimConfig(num_devices=5), tenant_id="acme")
            payload, _ = sim.payload(t=0.0)
            w3.write(publish_pkt("swx/telemetry", payload, qos=1, packet_id=3))
            await w3.drain()
            ptype, _, _ = await read_pkt(r3)
            assert ptype == 4  # PUBACK
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 5)
            for w in (w1, w2, w3):
                w.close()

    run(main())


def test_mqtt_command_topic_isolation(run):
    """ADVICE regression: a client may subscribe only to ITS OWN command
    topic; other devices' command topics and wildcard reaches into the
    command space get SUBACK failure 0x80."""

    async def main():
        sections = {"event-sources": {"receivers": [
            {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"}]},
            "rule-processing": {"model": None}}
        async with running_pipeline(num_devices=5, sections=sections) as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            r, w = await asyncio.open_connection("127.0.0.1", receiver.port)
            w.write(connect_pkt("dev-1"))
            await w.drain()
            await read_pkt(r)
            cases = [("swx/commands/dev-1", 0x00),   # own topic: granted
                     ("swx/commands/dev-2", 0x80),   # someone else's: denied
                     ("swx/commands/#", 0x80),       # whole command space
                     ("#", 0x80),                    # global wildcard
                     ("swx/+/dev-2", 0x80),          # wildcard into commands
                     # with broker fan-out, ANY other subscription is an
                     # eavesdropping grant → default-deny
                     ("swx/telemetry/x", 0x80)]
            for i, (topic, expect) in enumerate(cases):
                w.write(subscribe_pkt(topic, packet_id=20 + i))
                await w.drain()
                ptype, _, body = await read_pkt(r)
                assert ptype == 9 and body[2] == expect, (topic, body[2])
            w.close()

    run(main())


def test_mqtt_qos2_handshake_and_dedup(run):
    """ADVICE regression: QoS2 PUBLISH gets PUBREC (not a PUBACK mis-ack),
    PUBREL gets PUBCOMP, and a retransmitted QoS2 PUBLISH before PUBREL
    is processed exactly once."""

    async def main():
        sections = {"event-sources": {"receivers": [
            {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"}]},
            "rule-processing": {"model": None}}
        async with running_pipeline(num_devices=5, sections=sections) as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            r, w = await asyncio.open_connection("127.0.0.1", receiver.port)
            w.write(connect_pkt("dev-q2"))
            await w.drain()
            await read_pkt(r)
            sim = DeviceSimulator(SimConfig(num_devices=5), tenant_id="acme")
            payload, _ = sim.payload(t=0.0)
            # PUBLISH qos2 → PUBREC
            w.write(publish_pkt("swx/telemetry", payload, qos=2, packet_id=9))
            await w.drain()
            ptype, _, body = await read_pkt(r)
            assert ptype == 5 and body == (9).to_bytes(2, "big")  # PUBREC
            # retransmit (DUP) before PUBREL → PUBREC again, NOT re-ingested
            w.write(publish_pkt("swx/telemetry", payload, qos=2, packet_id=9))
            await w.drain()
            ptype, _, _ = await read_pkt(r)
            assert ptype == 5
            # PUBREL → PUBCOMP
            w.write(_pkt(6, 2, (9).to_bytes(2, "big")))
            await w.drain()
            ptype, _, body = await read_pkt(r)
            assert ptype == 7 and body == (9).to_bytes(2, "big")  # PUBCOMP
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 5)
            await asyncio.sleep(0.1)  # would catch a double-ingest
            assert em.telemetry.total_events == 5
            w.close()

    run(main())


def test_mqtt_rejects_wildcard_client_id(run):
    """Code-review regression: a client_id containing topic syntax could
    forge the own-command-topic authorization (client_id '#' makes
    'swx/commands/#' look like its own topic) — rejected at CONNECT."""

    async def main():
        sections = {"event-sources": {"receivers": [
            {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"}]},
            "rule-processing": {"model": None}}
        async with running_pipeline(num_devices=5, sections=sections) as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            for bad in ("#", "dev/+", "a/b"):
                r, w = await asyncio.open_connection("127.0.0.1",
                                                     receiver.port)
                w.write(connect_pkt(bad))
                await w.drain()
                ptype, _, body = await read_pkt(r)
                assert ptype == 2 and body[1] == 2, bad  # identifier rejected
                w.close()

    run(main())
