"""MQTT 3.1.1 ingest endpoint (services/mqtt.py): a hand-rolled client
speaks the real wire protocol — CONNECT/PUBLISH/SUBSCRIBE/PING — and the
full pipeline ingests its telemetry; command delivery rides the same
session [SURVEY.md §2.2 event-sources MQTT, command-delivery MQTT]."""

import asyncio

import numpy as np
import pytest

from sitewhere_tpu.services.mqtt import _encode_varint
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_pipeline import running_pipeline, wait_until


def _utf8(s: str) -> bytes:
    b = s.encode()
    return len(b).to_bytes(2, "big") + b


def _pkt(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + _encode_varint(len(body)) + body


def connect_pkt(client_id: str) -> bytes:
    body = _utf8("MQTT") + bytes([4, 2]) + (60).to_bytes(2, "big") \
        + _utf8(client_id)
    return _pkt(1, 0, body)


def publish_pkt(topic: str, payload: bytes, qos: int = 0,
                packet_id: int = 1) -> bytes:
    body = _utf8(topic)
    if qos:
        body += packet_id.to_bytes(2, "big")
    return _pkt(3, qos << 1, body + payload)


def subscribe_pkt(topic: str, packet_id: int = 7) -> bytes:
    return _pkt(8, 2, packet_id.to_bytes(2, "big") + _utf8(topic) + b"\x00")


async def read_pkt(reader) -> tuple[int, int, bytes]:
    (h,) = await reader.readexactly(1)
    mult, length = 1, 0
    while True:
        (b,) = await reader.readexactly(1)
        length += (b & 0x7F) * mult
        if not b & 0x80:
            break
        mult *= 128
    body = await reader.readexactly(length) if length else b""
    return h >> 4, h & 0x0F, body


def test_mqtt_ingest_and_command_roundtrip(run):
    async def main():
        from sitewhere_tpu.domain.events import DeviceCommandInvocation
        from sitewhere_tpu.domain.model import DeviceCommand
        from sitewhere_tpu.services import CommandDeliveryService

        sections = {
            "event-sources": {"receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"}]},
            "rule-processing": {"model": None},
            "command-delivery": {"provider": "mqtt", "encoder": "json"},
        }
        async with running_pipeline(num_devices=20, sections=sections,
                                    extra_services=(CommandDeliveryService,)) \
                as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            port = receiver.port
            reader, writer = await asyncio.open_connection("127.0.0.1", port)

            # CONNECT → CONNACK accepted
            writer.write(connect_pkt("dev-7"))
            await writer.drain()
            ptype, _, body = await read_pkt(reader)
            assert ptype == 2 and body[1] == 0

            # SUBSCRIBE to this device's command topic → SUBACK
            dm = rt.api("device-management").management("acme")
            device = dm.get_device_by_token("dev-7")
            writer.write(subscribe_pkt("swx/commands/dev-7"))
            await writer.drain()
            ptype, _, body = await read_pkt(reader)
            assert ptype == 9

            # PUBLISH telemetry (QoS1) → PUBACK + pipeline ingest
            sim = DeviceSimulator(SimConfig(num_devices=20), tenant_id="acme")
            for k in range(3):
                payload, _ = sim.payload(t=60.0 * k)
                writer.write(publish_pkt("swx/telemetry", payload, qos=1,
                                         packet_id=10 + k))
                await writer.drain()
                ptype, _, body = await read_pkt(reader)
                assert ptype == 4  # PUBACK

            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 60)

            # command invocation routes back down the SAME mqtt session
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="reboot", device_type_id=dt.id, name="reboot"))
            assignment = dm.get_active_assignments_for_device(device.id)[0]
            inv = DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id, parameter_values={"delay": 1})
            await em.add_command_invocations([inv])
            ptype, flags, body = await read_pkt(reader)
            assert ptype == 3  # PUBLISH down to the device
            tlen = int.from_bytes(body[:2], "big")
            topic = body[2:2 + tlen].decode()
            assert topic == "swx/commands/dev-7"
            assert b"reboot" in body[2 + tlen:]

            # PINGREQ → PINGRESP keeps the session alive
            writer.write(_pkt(12, 0, b""))
            await writer.drain()
            ptype, _, _ = await read_pkt(reader)
            assert ptype == 13
            writer.close()

    run(main())


def test_mqtt_rejects_garbage_and_survives(run):
    async def main():
        sections = {"event-sources": {"receivers": [
            {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"}]},
            "rule-processing": {"model": None}}
        async with running_pipeline(num_devices=5, sections=sections) as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            # a client that speaks garbage gets dropped without killing
            # the listener
            r1, w1 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w1.write(b"\xff\xff\xff\xff\xff\xff")
            await w1.drain()
            # a well-behaved client still connects fine afterwards
            r2, w2 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w2.write(connect_pkt("ok"))
            await w2.drain()
            ptype, _, body = await read_pkt(r2)
            assert ptype == 2 and body[1] == 0
            # garbage PUBLISH payload counts a decode failure, not a crash
            w2.write(publish_pkt("t", b"not swb1"))
            await w2.drain()
            await wait_until(lambda: rt.metrics.snapshot()
                             ["event_sources.decode_failures"] >= 1)
            w1.close()
            w2.close()

    run(main())
