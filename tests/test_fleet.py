"""Fleet control plane tests (sitewhere_tpu/fleet + parallel/placement).

The ISSUE-10 acceptance surface: deterministic weighted placement, the
drain-then-handoff invariant (old owner's engines stop and commit
BEFORE the new owner starts — never dual-ownership, at-least-once
across the move), automatic reassignment after a worker crash with
zero lost accepted events, the `GET /api/fleet` / `swx fleet status` /
`swx top` surfaces, autoscaler hysteresis/cooldown, and the
fleet.heartbeat / fleet.rebalance chaos sites healing under the
supervisor.

Topology: in-proc — N worker ServiceRuntimes (fleet_managed) share ONE
EventBus with a driver runtime hosting event-sources and the
controller. Same protocol, same records, same consumer groups as the
multi-process deployment (bench.py --workers); only the process
boundary is collapsed. HERMETIC since the fencing PR: tenant registry
state is seeded onto the shared bus (registry-state topic,
services/replication.py) and every worker adopts from bus replay —
each worker's data_dir is worker-LOCAL scratch, never a shared mount.
The fencing tests below pin the epoch-fencing protocol itself
(docs/FLEET.md): stale-epoch writes rejected, zombie owners demoted,
replay-adoption equivalent to snapshot-adoption.
"""

import asyncio
import contextlib

from sitewhere_tpu.cli import render_fleet, render_top
from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.fleet import AutoscalerPolicy, FleetController, FleetWorker
from sitewhere_tpu.kernel.observe import observe_report
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.parallel.placement import (
    compute_placement,
    placement_moves,
    rendezvous_rank,
)
from sitewhere_tpu.services import (
    DeviceManagementService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
    InstanceManagementService,
    RuleProcessingService,
)
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_pipeline import wait_until

DEVICES = 64

RP_SECTION = {"model": "zscore", "model_config": {"window": 8},
              "threshold": 6.0, "batch_window_ms": 1.0,
              "buckets": [DEVICES], "capacity": DEVICES}


# ---------------------------------------------------------------------------
# placement (pure)
# ---------------------------------------------------------------------------


def test_placement_deterministic_and_stable():
    tenants = {f"t{i}": 1.0 for i in range(40)}
    workers = ["w0", "w1", "w2", "w3"]
    a = compute_placement(tenants, workers)
    b = compute_placement(tenants, list(reversed(workers)))
    assert a == b, "placement must not depend on worker-list order"
    assert set(a) == set(tenants)
    counts = {w: sum(1 for t in a if a[t] == w) for w in workers}
    assert all(c > 0 for c in counts.values()), counts
    # rendezvous stability: removing one worker moves ONLY its tenants
    shrunk = compute_placement(tenants, ["w0", "w1", "w2"])
    moved = placement_moves(a, shrunk)
    assert set(moved) == {t for t, w in a.items() if w == "w3"}, (
        "removing w3 must only move w3's tenants")
    # determinism of the preference order itself
    assert rendezvous_rank("t0", workers) == rendezvous_rank("t0", workers)


def test_placement_respects_weights():
    # one heavy tenant (weight 8) + light ones: the capacity pass must
    # not stack more weight onto the heavy tenant's worker than the
    # headroom cap allows
    tenants = {"heavy": 8.0, **{f"t{i}": 1.0 for i in range(8)}}
    workers = ["w0", "w1"]
    placed = compute_placement(tenants, workers, headroom=1.1)
    load = {w: 0.0 for w in workers}
    for tid, w in placed.items():
        load[w] += tenants[tid]
    cap = 1.1 * sum(tenants.values()) / 2
    assert max(load.values()) <= cap + 8.0  # heavy itself may overshoot
    heavy_worker = placed["heavy"]
    lights_with_heavy = [t for t in placed
                         if placed[t] == heavy_worker and t != "heavy"]
    assert len(lights_with_heavy) <= 2, placed


def test_placement_empty_inputs():
    assert compute_placement({}, ["w0"]) == {}
    assert compute_placement({"t": 1.0}, []) == {}


# ---------------------------------------------------------------------------
# in-proc fleet harness
# ---------------------------------------------------------------------------


def _worker_runtime(bus, wid, data_dir, **overrides):
    rt = ServiceRuntime(InstanceSettings(
        instance_id="fleet-test", fleet_managed=True,
        fleet_heartbeat_s=0.2, observe_interval_ms=50.0,
        # worker-LOCAL scratch (registry WAL + snapshots) — adoption
        # state comes from bus replay, not this directory
        data_dir=str(data_dir / wid), **overrides), bus=bus)
    for cls in (DeviceManagementService, InboundProcessingService,
                EventManagementService, DeviceStateService,
                RuleProcessingService):
        rt.add_service(cls(rt))
    worker = FleetWorker(rt, wid)
    rt.add_child(worker)
    return rt, worker


async def _seed_registries(bus, cfgs, *, instance_id="fleet-test"):
    """Seed each tenant's device registry ONTO THE SHARED BUS
    (replicated tenant state, services/replication.py): the seeding
    runtime's bootstrap registrations land on the per-tenant
    registry-state topic, and whichever worker adopts (initially,
    after a migration, after a crash) rebuilds the same fleet from
    replay — no shared filesystem anywhere (docs/FLEET.md)."""
    seed = ServiceRuntime(InstanceSettings(
        instance_id=instance_id, registry_replication=True), bus=bus)
    seed.add_service(DeviceManagementService(seed))
    await seed.start()
    for cfg in cfgs:
        await seed.add_tenant(cfg)
        dm = seed.api("device-management").management(cfg.tenant_id)
        dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), DEVICES)
    await seed.stop()  # replicator seal: snapshot records on the bus


@contextlib.asynccontextmanager
async def fleet(tmp_path, n_workers=2, n_tenants=2, *, rest=False,
                policy=None, spawner=None, wire=False, wire_prefetch=True,
                wire_pipeline=True, wire_prefetch_credit=64):
    """In-proc fleet harness. With `wire=True` the workers attach to the
    driver's bus over a REAL BusServer socket (RemoteEventBus), so the
    wire data plane — streaming prefetch, pipelined produce, the codec
    — sits under every worker-side record; `wire_prefetch`/
    `wire_pipeline` are the fast-path A/B levers
    (tests/test_wire_prefetch.py re-runs the kill-drill and straddle
    invariants through it)."""
    cfgs = [TenantConfig(tenant_id=f"t{i}",
                         sections={"rule-processing": dict(RP_SECTION)})
            for i in range(n_tenants)]
    driver = ServiceRuntime(InstanceSettings(
        instance_id="fleet-test", fleet_interval_s=0.05,
        fleet_dead_after_s=1.5, rest_port=0))
    driver.add_service(EventSourcesService(driver))
    if rest:
        driver.add_service(InstanceManagementService(driver))
    controller = FleetController(
        driver,
        policy=policy or AutoscalerPolicy(min_workers=n_workers,
                                          max_workers=n_workers),
        spawner=spawner)
    driver.add_child(controller)
    await driver.start()
    broker = None
    if wire:
        from sitewhere_tpu.kernel.wire import BusServer

        broker = BusServer(driver.bus)
        await broker.start()
    await _seed_registries(driver.bus, cfgs)
    workers = {}
    runtimes = {}
    for i in range(n_workers):
        wid = f"w{i}"
        bus = driver.bus
        if wire:
            from sitewhere_tpu.kernel.wire import RemoteEventBus

            bus = RemoteEventBus("127.0.0.1", broker.port,
                                 prefetch=wire_prefetch,
                                 pipeline=wire_pipeline,
                                 prefetch_credit=wire_prefetch_credit)
            bus.owner = wid
        rt, worker = _worker_runtime(bus, wid, tmp_path)
        await rt.start()
        runtimes[wid] = rt
        workers[wid] = worker
    for cfg in cfgs:
        # local event-sources engines + (the driver hosts the
        # controller) fleet placement registration, one call
        await driver.add_tenant(cfg)
    await wait_until(lambda: controller.snapshot()["converged"],
                     timeout=120.0)
    try:
        yield driver, controller, runtimes, workers, cfgs
    finally:
        for rt in runtimes.values():
            if rt.status.value != "stopped":
                await rt.stop()
        if broker is not None:
            await broker.stop()
        await driver.stop()


class _Meter:
    """Scored-events counters per tenant off the shared bus."""

    def __init__(self, driver, cfgs):
        self.consumers = {c.tenant_id: driver.bus.subscribe(
            driver.naming.tenant_topic(c.tenant_id, "scored-events"),
            group="fleet-test-meter") for c in cfgs}
        self.scored = {c.tenant_id: 0 for c in cfgs}
        self.sent = {c.tenant_id: 0 for c in cfgs}
        self.sims = {c.tenant_id: DeviceSimulator(
            SimConfig(num_devices=DEVICES), tenant_id=c.tenant_id)
            for c in cfgs}
        self.driver = driver
        self._k = 0

    async def submit_round(self):
        for tid, sim in self.sims.items():
            receiver = self.driver.api("event-sources") \
                .engine(tid).receiver("default")
            if await receiver.submit(sim.payload(t=1000.0 + self._k)[0]):
                self.sent[tid] += DEVICES
        self._k += 1

    def drain(self):
        for tid, consumer in self.consumers.items():
            for record in consumer.poll_nowait(max_records=256):
                self.scored[tid] += len(record.value)

    async def drain_until_caught_up(self, timeout=90.0):
        def caught_up():
            self.drain()
            return all(self.scored[t] >= self.sent[t] for t in self.sent)

        await wait_until(caught_up, timeout=timeout)

    def close(self):
        for consumer in self.consumers.values():
            consumer.close()


async def _crash(runtimes, workers, wid):
    """Kill a worker with crash fidelity: no leave, no releases — its
    loops just stop and its engines vanish (in-proc stand-in for
    SIGKILL; the consumers leave their groups exactly as the broker's
    on_disconnect reaps a dead wire peer's). On a wire-attached worker
    the client is KILLED first (socket drops, no reconnect, no final
    commits), so the broker sees exactly what a SIGKILLed process
    leaves behind — including a prefetch credit window mid-flight."""
    worker = workers.pop(wid)
    rt = runtimes.pop(wid)
    client = getattr(rt.bus, "_client", None)
    if client is not None:
        client.kill()
    for loop in (worker._control, worker._apply):
        if loop._task is not None:
            loop._task.cancel()
    worker.owned.clear()          # _do_stop must not release/announce
    rt.remove_child(worker)
    try:
        await rt.stop()
    except Exception:  # noqa: BLE001 - crash fidelity: a SIGKILLed
        # process runs no stop path at all; with the wire client killed,
        # stop-path produces (replicator seal, final commits) fail — the
        # partial teardown IS the crash being simulated
        if client is None:
            raise


# ---------------------------------------------------------------------------
# handoff invariant: migration
# ---------------------------------------------------------------------------


def test_fleet_migration_drain_then_handoff(run, tmp_path):
    async def main():
        async with fleet(tmp_path, n_workers=2, n_tenants=2) as (
                driver, controller, runtimes, workers, cfgs):
            meter = _Meter(driver, cfgs)
            for _ in range(4):
                await meter.submit_round()
            await meter.drain_until_caught_up()
            before = dict(meter.scored)
            assert all(v > 0 for v in before.values())

            # migrate t0 to the worker that does NOT own it
            source = controller.snapshot()["assignment"]["t0"]
            target = next(w for w in workers if w != source)
            controller.migrate("t0", target)
            await wait_until(
                lambda: controller.snapshot()["assignment"].get("t0")
                == target and controller.snapshot()["converged"],
                timeout=60.0)

            # THE invariant: the old owner released (engines stopped,
            # release published) strictly before the new owner adopted
            assert workers[source].released_at["t0"] \
                <= workers[target].adopted_at["t0"]
            assert "t0" not in runtimes[source].tenants
            assert "t0" in runtimes[target].tenants

            # committed-offset resume: post-migration traffic scores
            # (and nothing accepted before the move was lost)
            for _ in range(3):
                await meter.submit_round()
            await meter.drain_until_caught_up()
            assert meter.scored["t0"] >= meter.sent["t0"]

            # handoff accounting
            snap = driver.metrics.snapshot()
            assert snap.get("fleet.rebalances", 0) >= 2
            assert runtimes[target].metrics.counter(
                "fleet.handoffs").value >= 1
            meter.close()

    run(main())


# ---------------------------------------------------------------------------
# worker death: reassignment, zero loss, operator surfaces
# ---------------------------------------------------------------------------


def test_worker_crash_reassigns_with_zero_loss(run, tmp_path):
    async def main():
        async with fleet(tmp_path, n_workers=2, n_tenants=2,
                         rest=True) as (
                driver, controller, runtimes, workers, cfgs):
            meter = _Meter(driver, cfgs)
            for _ in range(3):
                await meter.submit_round()
            await meter.drain_until_caught_up()

            # kill the worker owning t0 MID-FLOOD: keep accepting events
            # through the crash and the reassignment window
            victim = controller.snapshot()["assignment"]["t0"]
            survivor = next(w for w in workers if w != victim)
            await meter.submit_round()
            await _crash(runtimes, workers, victim)
            for _ in range(4):
                await meter.submit_round()
                await asyncio.sleep(0.05)

            # the controller declares the victim dead and reassigns;
            # the survivor adopts WITHOUT waiting on a release (the
            # dead cannot ack) and resumes from committed offsets
            await wait_until(
                lambda: victim not in controller.snapshot()["workers"],
                timeout=30.0)
            await wait_until(
                lambda: controller.snapshot()["converged"], timeout=120.0)
            snap = controller.snapshot()
            assert all(w == survivor for w in snap["assignment"].values())
            assert driver.metrics.counter("fleet.worker_deaths").value >= 1

            # zero lost accepted events: everything the ingress accepted
            # is scored (exactly-once-or-replayed — scored >= accepted)
            for _ in range(2):
                await meter.submit_round()
            await meter.drain_until_caught_up(timeout=120.0)
            for tid in meter.sent:
                assert meter.scored[tid] >= meter.sent[tid], (
                    tid, meter.sent[tid], meter.scored[tid])

            # operator surfaces reflect the new placement:
            # GET /api/fleet over real HTTP...
            from tests.test_fleet import _http_get_fleet

            report = await _http_get_fleet(driver)
            assert set(report["workers"]) == {survivor}
            assert all(w == survivor
                       for w in report["assignment"].values())
            # ...and the swx top / swx fleet renderings
            text = render_fleet(report)
            assert survivor in text and "fleet epoch" in text
            top = render_top(observe_report(driver))
            assert "fleet epoch" in top and survivor in top
            meter.close()

    run(main())


async def _http_get_fleet(driver) -> dict:
    """JWT dance + GET /api/fleet against the driver's live REST port."""
    import base64
    import json as _json

    from sitewhere_tpu.cli import _http_json

    port = driver.services["instance-management"].rest.port
    basic = base64.b64encode(b"admin:password").decode()
    status, out = await _http_json(
        "POST", "127.0.0.1", port, "/api/jwt",
        headers={"Authorization": f"Basic {basic}"})
    assert status == 200, (status, out)
    status, report = await _http_json(
        "GET", "127.0.0.1", port, "/api/fleet",
        headers={"Authorization": f"Bearer {out['token']}"})
    assert status == 200, (status, report)
    return _json.loads(_json.dumps(report))


# ---------------------------------------------------------------------------
# autoscaler decisions (hysteresis + cooldown)
# ---------------------------------------------------------------------------


def test_autoscaler_decisions_hysteresis_and_cooldown():
    rt = ServiceRuntime(InstanceSettings(instance_id="fleet-unit"))
    controller = FleetController(rt, policy=AutoscalerPolicy(
        min_workers=1, max_workers=4, scale_up_lag=1000.0,
        scale_down_lag=100.0, hysteresis=0.8, cooldown_s=10.0,
        imbalance_ratio=3.0))
    controller._last_scale_t = -1e9

    # scale up: mean load per worker above the up threshold
    decision = controller.decide({"w0": 3000.0, "w1": 100.0}, {})
    assert decision and decision["action"] == "add_replica"

    # cooldown: an immediately-following decision is suppressed
    import time

    controller._last_scale_t = time.monotonic()
    assert controller.decide({"w0": 9000.0, "w1": 9000.0}, {}) is None
    controller._last_scale_t = -1e9

    # hysteresis band: below up, above down×hysteresis → hold
    assert controller.decide({"w0": 150.0, "w1": 150.0}, {}) is None

    # scale down: quiet fleet sheds its coolest worker
    decision = controller.decide({"w0": 10.0, "w1": 50.0}, {})
    assert decision and decision["action"] == "remove_replica"
    assert decision["worker"] == "w0"

    # replace-below-floor ignores cooldown (a dead worker must be
    # replaced promptly)
    controller._last_scale_t = time.monotonic()
    decision = controller.decide({}, {})
    assert decision and decision["action"] == "add_replica"

    # migration: one hot worker owning several tenants, fleet balanced
    # enough that a move beats a new replica
    from sitewhere_tpu.fleet.controller import _WorkerState

    controller._last_scale_t = -1e9
    controller.tenants = {"a": None, "b": None, "c": None}
    controller.workers = {
        "w0": _WorkerState(last_seen=time.monotonic(),
                           owned=("a", "b"), signals={}),
        "w1": _WorkerState(last_seen=time.monotonic(),
                           owned=("c",), signals={}),
    }
    decision = controller.decide({"w0": 700.0, "w1": 10.0},
                                 {"a": 650.0, "b": 50.0, "c": 10.0})
    assert decision and decision["action"] == "migrate_tenant", decision
    assert decision["tenant"] == "a" and decision["worker"] == "w1"


def test_worker_retirement_drains_and_exits(run, tmp_path):
    """Scale-down end to end: a retired worker keeps heartbeating (so
    peers can still wait on its releases), hands every tenant to the
    survivors, and flags itself retired — the process entry exits on
    that flag."""

    async def main():
        async with fleet(tmp_path, n_workers=2, n_tenants=2) as (
                driver, controller, runtimes, workers, cfgs):
            meter = _Meter(driver, cfgs)
            await meter.submit_round()
            await meter.drain_until_caught_up()

            victim = controller.snapshot()["assignment"]["t0"]
            survivor = next(w for w in workers if w != victim)
            controller.retire_worker(victim)
            await wait_until(lambda: workers[victim].retired,
                             timeout=60.0)
            snap = controller.snapshot()
            assert all(w == survivor for w in snap["assignment"].values())
            assert not runtimes[victim].tenants
            # drain-then-handoff held through the retirement
            for tid in snap["assignment"]:
                if tid in workers[victim].released_at \
                        and tid in workers[survivor].adopted_at:
                    assert workers[victim].released_at[tid] \
                        <= workers[survivor].adopted_at[tid]
            # traffic still scores on the survivor
            await meter.submit_round()
            await meter.drain_until_caught_up()
            meter.close()

    run(main())


# ---------------------------------------------------------------------------
# chaos: the fleet's own fault sites heal under the supervisor
# ---------------------------------------------------------------------------


def test_fleet_chaos_sites_heal(run, tmp_path):
    from sitewhere_tpu.kernel.faults import FaultInjector

    async def main():
        async with fleet(tmp_path, n_workers=1, n_tenants=1) as (
                driver, controller, runtimes, workers, cfgs):
            wid, rt = next(iter(runtimes.items()))

            # fleet.heartbeat: the worker's control loop crashes once,
            # restarts under the supervisor, and heartbeats resume —
            # the worker is never declared dead
            rt.install_faults(FaultInjector(seed=3).arm(
                "fleet.heartbeat", rate=1.0, max_faults=1))
            seq_before = controller.workers[wid].seq
            await wait_until(
                lambda: workers[wid]._control.restart_count >= 1,
                timeout=30.0)
            await wait_until(
                lambda: controller.workers.get(wid) is not None
                and controller.workers[wid].seq > seq_before + 1,
                timeout=30.0)
            assert wid in controller.snapshot()["workers"]

            # fleet.rebalance: the controller loop crashes mid-publish,
            # restarts, recovers its epoch off the control topic, and
            # the pending rebalance still lands
            driver.install_faults(FaultInjector(seed=4).arm(
                "fleet.rebalance", rate=1.0, max_faults=1))
            epoch_before = controller.epoch
            extra = TenantConfig(tenant_id="late",
                                 sections={"rule-processing":
                                           dict(RP_SECTION)})
            await driver.add_tenant(extra)  # CRUD feeds placement
            await wait_until(
                lambda: controller._loop.restart_count >= 1, timeout=30.0)
            await wait_until(
                lambda: controller.snapshot()["assignment"].get("late")
                == wid, timeout=60.0)
            assert controller.epoch > epoch_before
            # the injected crashes were quarantine-free (no poison
            # record involved) and bounded — the fleet is converged
            await wait_until(
                lambda: controller.snapshot()["converged"], timeout=60.0)

    run(main())


# ---------------------------------------------------------------------------
# epoch fencing (docs/FLEET.md fencing protocol)
# ---------------------------------------------------------------------------


def test_fence_authority_rules(run):
    """The broker-side ownership table mirrors drain-then-handoff:
    old owner fenced-in until its release while live, fenced OUT
    immediately when the placement says it is dead — and a stale-epoch
    produce/commit raises the DISTINCT FencedError, never a generic
    failure."""
    from sitewhere_tpu.kernel.bus import EventBus, FencedError

    async def main():
        bus = EventBus()
        ctl = "fx.instance.fleet-control"
        topic = "fx.tenant.t0.inbound-events"
        await bus.produce(ctl, {"kind": "placement", "epoch": 1,
                                "assignment": {"t0": "w0"},
                                "workers": ["w0", "w1"]})
        # the owner writes
        await bus.produce(topic, {"n": 1}, fence=["t0", 1, "w0"])
        # unfenced writes (ingress, control plane) always pass
        await bus.produce(topic, {"n": 2})
        # move t0 to w1 with w0 LIVE and actually owning (prev map —
        # the controller's actual-owner view): w0 keeps writing through
        # its drain; w1 must NOT write before the release
        await bus.produce(ctl, {"kind": "placement", "epoch": 2,
                                "assignment": {"t0": "w1"},
                                "prev": {"t0": "w0"},
                                "workers": ["w0", "w1"]})
        await bus.produce(topic, {"n": 3}, fence=["t0", 1, "w0"])
        import pytest

        with pytest.raises(FencedError):
            await bus.produce(topic, {"n": 4}, fence=["t0", 2, "w1"])
        # release transfers ownership; the zombie's next write rejects
        await bus.produce(ctl, {"kind": "release", "tenant": "t0",
                                "worker": "w0", "epoch": 2})
        await bus.produce(topic, {"n": 5}, fence=["t0", 2, "w1"])
        with pytest.raises(FencedError) as exc_info:
            await bus.produce(topic, {"n": 6}, fence=["t0", 1, "w0"])
        assert exc_info.value.tenant == "t0"
        # dead old owner: the transfer is IMMEDIATE (the zombie window
        # closed by construction, no release needed from a corpse)
        await bus.produce(ctl, {"kind": "placement", "epoch": 3,
                                "assignment": {"t0": "w0"},
                                "prev": {"t0": "w1"},
                                "workers": ["w0"]})  # w1 dead
        with pytest.raises(FencedError):
            await bus.produce(topic, {"n": 7}, fence=["t0", 2, "w1"])
        await bus.produce(topic, {"n": 8}, fence=["t0", 3, "w0"])
        # stale-epoch COMMIT rejected too — a zombie can never move a
        # tenant group's offsets (the loss direction of dual ownership)
        consumer = bus.subscribe(topic, group="t0.inbound-processing")
        consumer.poll_nowait()
        before = dict(bus._groups["t0.inbound-processing"].committed)
        with pytest.raises(FencedError):
            consumer.commit(fence=["t0", 2, "w1"])
        assert bus._groups["t0.inbound-processing"].committed == before
        consumer.commit(fence=["t0", 3, "w0"])
        assert bus._groups["t0.inbound-processing"].committed != before
        assert bus.fences.rejections >= 4
        # assignment churn before the first assignee ever adopted: the
        # authority must key off the ACTUAL owner (`prev`), not the
        # assignment — or the rightful adopter waits on a release from
        # a worker that never owned the tenant (the measured wedge:
        # adopt → fence → release loop on a replacement worker)
        await bus.produce(ctl, {"kind": "placement", "epoch": 4,
                                "assignment": {"t0": "w1"},
                                "prev": {"t0": "w0"},
                                "workers": ["w0", "w1"]})
        await bus.produce(ctl, {"kind": "placement", "epoch": 5,
                                "assignment": {"t0": "w2"},
                                "prev": {},  # w0 released; nobody owns
                                "workers": ["w0", "w1", "w2"]})
        # w2 never waits on w1 (which never owned t0): write accepted
        await bus.produce(topic, {"n": 9}, fence=["t0", 5, "w2"])
        consumer.close()

    run(main())


def test_zombie_owner_fenced_and_demoted(run, tmp_path):
    """THE dual-ownership window, closed: a worker that goes deaf+mute
    (SIGSTOP analog — heartbeats stop, placements unseen) past
    dead_after is declared dead and its tenants reassign; when its
    engines keep consuming on stale state, the broker REJECTS their
    writes (fenced), the worker self-demotes (stops engines, publishes
    no release), and nothing accepted is lost."""

    async def main():
        async with fleet(tmp_path, n_workers=2, n_tenants=2) as (
                driver, controller, runtimes, workers, cfgs):
            meter = _Meter(driver, cfgs)
            for _ in range(3):
                await meter.submit_round()
            await meter.drain_until_caught_up()

            victim = controller.snapshot()["assignment"]["t0"]
            survivor = next(w for w in workers if w != victim)
            zombie = workers[victim]
            zombie_rt = runtimes[victim]

            # zombify: heartbeats stop, control records unseen — but the
            # engines (consumer loops, scoring, egress) keep running on
            # the stale placement view. This is SIGSTOP-then-SIGCONT
            # fidelity without the process boundary.
            async def _mute():
                return None

            zombie.heartbeat = _mute
            zombie.handle_control = lambda value: None

            # keep traffic flowing through the death + reassignment
            # window so the zombie has live records to (try to) write
            rejections0 = (driver.bus.fences.rejections
                           if driver.bus.fences is not None else 0)
            for _ in range(40):
                await meter.submit_round()
                await asyncio.sleep(0.05)
                if victim not in controller.snapshot()["workers"]:
                    break
            assert victim not in controller.snapshot()["workers"], \
                "controller never declared the mute worker dead"

            # the survivor adopts (dead owners can't ack) and the
            # zombie's fenced engines are stopped by its own apply loop
            await wait_until(
                lambda: "t0" not in zombie_rt.tenants
                and zombie_rt.fence.token("t0") is None, timeout=60.0)
            await wait_until(
                lambda: controller.snapshot()["owners"].get("t0")
                == survivor, timeout=60.0)
            # the zombie TRIED to write and was refused — the window is
            # closed by rejection, not by a grace timer
            assert driver.bus.fences is not None
            assert driver.bus.fences.rejections > rejections0
            assert driver.metrics.counter("fence.rejections").value > 0
            # the fenced demotion published NO release record under the
            # stale epoch — ownership moved via the fence authority
            fencing = controller.snapshot()["fencing"]
            assert fencing["owners"]["t0"]["worker"] == survivor

            # zero lost accepted events: everything accepted through
            # the false-positive death is scored by somebody
            for _ in range(2):
                await meter.submit_round()
            await meter.drain_until_caught_up(timeout=120.0)
            for tid in meter.sent:
                assert meter.scored[tid] >= meter.sent[tid], (
                    tid, meter.sent[tid], meter.scored[tid])
            meter.close()

    run(main())


def test_inflight_straddle_lands_exactly_once(run, tmp_path):
    """A drain-then-handoff migration under continuous flood: batches
    in flight when the epoch bumps land EXACTLY once — the loser's
    release commits through its settle barrier before the adopter
    resumes from committed offsets, so a clean handoff produces zero
    replays and zero losses (the at-least-once bound tightens to
    exactly-once when nobody crashes)."""

    async def main():
        async with fleet(tmp_path, n_workers=2, n_tenants=2) as (
                driver, controller, runtimes, workers, cfgs):
            meter = _Meter(driver, cfgs)
            await meter.submit_round()
            await meter.drain_until_caught_up()

            source = controller.snapshot()["assignment"]["t0"]
            target = next(w for w in workers if w != source)
            controller.migrate("t0", target)
            # flood WHILE the handoff runs: some batches straddle the
            # epoch bump (admitted by the loser, scored by either side)
            for _ in range(12):
                await meter.submit_round()
                await asyncio.sleep(0.02)
            await wait_until(
                lambda: controller.snapshot()["owners"].get("t0")
                == target and controller.snapshot()["converged"],
                timeout=60.0)
            for _ in range(2):
                await meter.submit_round()
            await meter.drain_until_caught_up(timeout=120.0)
            # exactly once: scored == sent (>= is loss, > is duplicate)
            for tid in meter.sent:
                assert meter.scored[tid] == meter.sent[tid], (
                    tid, meter.sent[tid], meter.scored[tid])
            meter.close()

    run(main())


# ---------------------------------------------------------------------------
# replicated tenant state: hermetic adoption + the WAL crash bound
# ---------------------------------------------------------------------------


def test_adoption_by_replay_equals_adoption_by_snapshot(run, tmp_path):
    """The state-equivalence pin: a worker with an EMPTY local data_dir
    adopting from bus replay ends with the same registry — and scores
    the same events identically — as one restoring the legacy shared
    registry.snap."""
    import numpy as np

    from sitewhere_tpu.kernel.bus import EventBus

    def _norm(snap):
        return {name: sorted((e.id, getattr(e, "token", ""),
                              getattr(e, "index", -1),
                              getattr(e, "status", ""))
                             for e in snap["tables"][name])
                for name in snap["tables"]}

    async def _build(instance_id, bus, settings_kw, cfg):
        rt = ServiceRuntime(InstanceSettings(
            instance_id=instance_id, **settings_kw), bus=bus)
        for cls in (DeviceManagementService, EventSourcesService,
                    InboundProcessingService, EventManagementService,
                    DeviceStateService, RuleProcessingService):
            rt.add_service(cls(rt))
        await rt.start()
        await rt.add_tenant(cfg)
        return rt

    async def _score_round(rt, tid, sim):
        consumer = rt.bus.subscribe(
            rt.naming.tenant_topic(tid, "scored-events"),
            group="equiv-meter")
        receiver = rt.api("event-sources").engine(tid).receiver("default")
        sent = 0
        # one device is deactivated below: each submit scores
        # DEVICES - 1 events (the unregistered split drops the rest)
        for k in range(3):
            if await receiver.submit(sim.payload(t=2000.0 + k)[0]):
                sent += DEVICES - 1
        out = []

        def caught_up():
            for record in consumer.poll_nowait(max_records=256):
                scored = record.value
                for i in range(len(scored)):
                    out.append((int(scored.device_index[i]),
                                round(float(scored.score[i]), 5),
                                bool(scored.is_anomaly[i])))
            return len(out) >= sent

        await wait_until(caught_up, timeout=60.0)
        consumer.close()
        return sorted(out)

    async def main():
        shared = tmp_path / "shared"
        cfg = TenantConfig(tenant_id="eq",
                           sections={"rule-processing": dict(RP_SECTION)})
        # seed: replication on AND a disk snapshot — the same history
        # feeds both adoption paths
        seed_bus = EventBus()
        seed = ServiceRuntime(InstanceSettings(
            instance_id="equiv", data_dir=str(shared),
            registry_replication=True), bus=seed_bus)
        seed.add_service(DeviceManagementService(seed))
        await seed.start()
        await seed.add_tenant(cfg)
        dm = seed.api("device-management").management("eq")
        dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), DEVICES)
        # a post-bootstrap mutation both paths must carry (status
        # matters: the registered mask gates scoring)
        dm.set_device_status(dm.get_device_by_token("dev-1").id,
                             "inactive")
        expected = _norm(dm.spi.to_snapshot())
        await seed.stop()

        # path A — bus replay: EMPTY local data_dir, same bus
        rt_a = await _build(
            "equiv", seed_bus,
            {"registry_replication": True}, cfg)
        dm_a = rt_a.api("device-management").management("eq")
        assert dm_a.restored_from == "bus-replay"
        # path B — legacy shared snapshot: fresh bus, shared data_dir
        rt_b = await _build(
            "equiv", EventBus(),
            {"registry_replication": False, "data_dir": str(shared)},
            cfg)
        dm_b = rt_b.api("device-management").management("eq")
        assert dm_b.restored_from == "snapshot+wal"

        assert _norm(dm_a.spi.to_snapshot()) == expected
        assert _norm(dm_b.spi.to_snapshot()) == expected
        idx = np.arange(DEVICES)
        assert (dm_a.registered_mask(idx) == dm_b.registered_mask(idx)).all()
        assert not dm_a.registered_mask(np.asarray([1]))[0]

        sim = DeviceSimulator(SimConfig(num_devices=DEVICES),
                              tenant_id="eq")
        scored_a = await _score_round(rt_a, "eq", sim)
        sim_b = DeviceSimulator(SimConfig(num_devices=DEVICES),
                                tenant_id="eq")
        scored_b = await _score_round(rt_b, "eq", sim_b)
        assert scored_a == scored_b and scored_a, (
            len(scored_a), len(scored_b))
        await rt_a.stop()
        await rt_b.stop()

    run(main())


def test_registry_wal_tightens_crash_bound(run, tmp_path):
    """Registrations after the last snapshot survive a hard crash via
    the WAL: the crash bound is the last APPENDED record, not the
    snapshot interval."""

    async def main():
        data = tmp_path / "node"
        rt = ServiceRuntime(InstanceSettings(
            instance_id="walcrash", data_dir=str(data)))
        rt.add_service(DeviceManagementService(rt))
        await rt.start()
        # huge snapshot interval: the debounced snapshotter can never
        # run before the "crash" below
        await rt.add_tenant(TenantConfig(
            tenant_id="t0",
            sections={"device-management":
                      {"snapshot_interval_s": 3600.0}}))
        dm = rt.api("device-management").management("t0")
        dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 8)
        assert rt.metrics.counter("fence.wal_appends").value > 0
        # HARD CRASH: no engine stop, no save_now — abandon the runtime
        # (the WAL fsynced every mutation as it happened)
        wal_path = data / "tenants" / "t0" / "registry.wal"
        assert wal_path.exists() and wal_path.stat().st_size > 0
        snap_path = data / "tenants" / "t0" / "registry.snap"
        assert not snap_path.exists()

        rt2 = ServiceRuntime(InstanceSettings(
            instance_id="walcrash2", data_dir=str(data)))
        rt2.add_service(DeviceManagementService(rt2))
        await rt2.start()
        await rt2.add_tenant(TenantConfig(tenant_id="t0"))
        dm2 = rt2.api("device-management").management("t0")
        assert dm2.restored_from == "snapshot+wal"
        assert dm2.spi.device_count() == 8
        assert dm2.spi.get_device_by_token("dev-3") is not None
        import numpy as np

        assert dm2.registered_mask(np.arange(8)).all()
        await rt2.stop()
        # engines from the abandoned runtime hold the old WAL file open;
        # that is fine — replay reads by path
        for svc in rt.services.values():
            svc.engines.clear()

    run(main())


# ---------------------------------------------------------------------------
# wire surface: the broker serves group lags to remote peers
# ---------------------------------------------------------------------------


def test_wire_group_lags_op(run):
    from sitewhere_tpu.kernel.bus import EventBus
    from sitewhere_tpu.kernel.wire import BusServer, RemoteEventBus

    async def main():
        bus = EventBus()
        await bus.produce("fleet-test.tenant.acme.inbound-events", {"n": 1},
                          key="d1")
        consumer = bus.subscribe("fleet-test.tenant.acme.inbound-events",
                                 group="acme.inbound-processing")
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port)
        await remote.initialize()
        import inspect

        lags = remote.group_lags()
        assert inspect.isawaitable(lags)
        lag_map = await lags
        assert lag_map["acme.inbound-processing"][
            "fleet-test.tenant.acme.inbound-events"] == 1
        consumer.close()
        await remote.stop()
        await server.stop()

    run(main())


# ---------------------------------------------------------------------------
# broker-side member eviction on death declarations (kernel/bus.py)


def test_broker_evicts_dead_workers_members(run):
    """ROADMAP item 4's remaining thread, closed: a placement record
    that DROPS a worker from the live list (the controller's death
    declaration) evicts that worker's owner-tagged consumer-group
    members broker-side — the zombie's partitions reassign to surviving
    members NOW instead of stalling until SIGCONT, its late commits are
    refused, and its polls read nothing through the stale assignment."""
    import pytest

    from sitewhere_tpu.kernel.bus import EventBus
    from sitewhere_tpu.kernel.metrics import MetricsRegistry

    async def main():
        bus = EventBus(default_partitions=4)
        bus.metrics = MetricsRegistry()
        topic = "swx1.tenant.t0.outbound-enriched-events"
        control = "swx1.instance.fleet-control"
        zombie = bus.subscribe(topic, group="t0.rule-processing",
                               owner="w0")
        await bus.produce(control, {
            "kind": "placement", "epoch": 1,
            "assignment": {"t0": "w0"}, "prev": {},
            "workers": ["w0", "w1"]}, key="placement")
        for i in range(8):
            await bus.produce(topic, {"n": i}, key=f"d{i}")
        # the successor joins the SAME group: without eviction the
        # rebalance splits partitions 2/2 with a member that can never
        # poll again — half the topic stalls
        successor = bus.subscribe(topic, group="t0.rule-processing",
                                  owner="w1")
        assert len(zombie.assignment) == 2
        assert len(successor.assignment) == 2
        # w0's own fleet-control subscription (broadcast group, no
        # partition contention) must SURVIVE its eviction: a falsely
        # declared worker that resumes still needs to see placements
        control_sub = bus.subscribe(control, group="fleet.worker.w0",
                                    owner="w0")
        # the death declaration: w0 absent from the live-worker list
        await bus.produce(control, {
            "kind": "placement", "epoch": 2,
            "assignment": {"t0": "w1"}, "prev": {"t0": "w0"},
            "workers": ["w1"]}, key="placement")
        assert zombie.evicted and zombie._closed
        assert len(successor.assignment) == 4  # all partitions, now
        assert bus.metrics.counter("fleet.members_evicted").value == 1
        # the control subscription rode through: not evicted, still
        # assigned, still reading (resumed workers stay reachable)
        assert not control_sub.evicted and not control_sub._closed
        assert control_sub.poll_nowait(max_records=8)
        # the zombie's stale assignment reads nothing...
        assert zombie.poll_nowait(max_records=64) == []
        # ...and its late commit is refused (the unfenced-group analog
        # of the data-path FencedError)
        with pytest.raises(RuntimeError, match="evicted"):
            zombie.commit({(topic, 0): 5})
        # a FENCED commit still raises the TYPED error (fence checked
        # BEFORE the eviction refusal): the wire client's on_fenced
        # signal path — the worker's "you lost ownership" — survives
        # eviction
        from sitewhere_tpu.kernel.bus import FencedError

        with pytest.raises(FencedError):
            zombie.commit({(topic, 0): 5}, fence=["t0", 1, "w0"])
        # the successor drains the whole topic
        records = []
        while True:
            got = successor.poll_nowait(max_records=64)
            if not got:
                break
            records.extend(got)
        assert len(records) == 8
        # a REJOINED worker's fresh members are untouched: eviction
        # fires only on live-list DROP transitions
        await bus.produce(control, {
            "kind": "placement", "epoch": 3,
            "assignment": {"t0": "w1"}, "prev": {"t0": "w1"},
            "workers": ["w0", "w1"]}, key="placement")
        fresh = bus.subscribe(topic, group="t0.rule-processing",
                              owner="w0")
        await bus.produce(control, {
            "kind": "placement", "epoch": 4,
            "assignment": {"t0": "w1"}, "prev": {"t0": "w1"},
            "workers": ["w0", "w1"]}, key="placement")
        assert not fresh.evicted
        # a graceful leave (worker closed its consumers itself) makes
        # the eviction a counted no-op
        fresh.close()
        await bus.produce(control, {
            "kind": "placement", "epoch": 5,
            "assignment": {"t0": "w1"}, "prev": {"t0": "w1"},
            "workers": ["w1"]}, key="placement")
        assert bus.metrics.counter("fleet.members_evicted").value == 1
        successor.close()

    run(main())


def test_wire_subscribe_threads_owner_tag(run):
    """A fleet worker's RemoteEventBus owner-tags every membership it
    registers (fleet/worker_main sets bus.owner), so broker-side
    eviction can attribute members to workers across the wire."""
    from sitewhere_tpu.kernel.bus import EventBus
    from sitewhere_tpu.kernel.wire import BusServer, RemoteEventBus

    async def main():
        bus = EventBus()
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port)
        remote.owner = "w7"
        await remote.initialize()
        consumer = remote.subscribe("swx1.tenant.t0.inbound-events",
                                    group="t0.inbound-processing")
        await consumer.poll(max_records=1, timeout=0.05)  # binds the cid
        members = bus._groups["t0.inbound-processing"].members
        assert [m.owner for m in members] == ["w7"]
        # eviction over the wire: the broker closes the member; the
        # remote's next poll finds nothing and its commit is refused
        assert bus.evict_owner("w7") == 1
        assert await consumer.poll(max_records=8, timeout=0.05) == []
        consumer.close()
        await remote.stop()
        await server.stop()

    run(main())
