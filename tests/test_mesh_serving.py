"""Mesh-sharded megabatch serving + self-tuning dispatch (ISSUE 12).

The conftest forces 8 virtual CPU host-platform devices, so every test
here exercises a REAL {data: 4, model: 2} device mesh — sharding
regressions fail in tier-1, not only on TPU rigs.

- wiring/fit: tenant `rule-processing: {mesh}` and the instance
  `scoring_mesh_*` defaults thread to the shared pool; an oversized
  spec fits down to the devices this process has (mesh_from_spec).
- mesh on/off equivalence: identical per-tenant scores, telemetry,
  alerts, and committed offsets under a forced 8-device mesh — the
  sharding changes placement, never behavior.
- hot-swap + add/remove under a SHARDED stack: the donated param swap
  and capacity growth keep the model-axis placement and the version
  fence (attribution never tears).
- self-tuning: the adaptive megabatch window and the egress lane
  auto-tuner converge under sustained signals and never flap
  (hysteresis bands + cooldowns, pinned here).
"""

import contextlib

import jax
import numpy as np
import pytest

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.kernel.metrics import MetricsRegistry
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.models import build_model
from sitewhere_tpu.parallel.mesh import mesh_from_spec
from sitewhere_tpu.persistence.telemetry import TelemetryStore
from sitewhere_tpu.scoring.pool import PoolConfig, SharedScoringPool
from sitewhere_tpu.services import (
    DeviceManagementService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
    RuleProcessingService,
)
from tests.test_megabatch import (
    RULE,
    TENANTS,
    _batch,
    _drive_tenants,
    megabatch_runtime,
)
from tests.test_pipeline import wait_until

MESH = {"data": 4, "model": 2}


# -- wiring / fit -----------------------------------------------------------

def test_mesh_from_spec_fits_available_devices():
    assert jax.device_count() == 8  # the conftest contract
    m = mesh_from_spec(MESH)
    assert dict(m.shape) == {"data": 4, "model": 2}
    # oversized: 8x2 wants 16 devices — shrink, keep the axis roles
    fit = mesh_from_spec({"data": 8, "model": 2})
    assert dict(fit.shape) == {"data": 4, "model": 2}
    # model axis larger than the device count: largest divisor wins
    fit = mesh_from_spec({"data": 16, "model": 16})
    assert dict(fit.shape) == {"data": 1, "model": 8}
    # no spec → no mesh (the single-device stacked dispatch)
    assert mesh_from_spec(None) is None
    assert mesh_from_spec({}) is None


def test_mesh_wiring_tenant_and_instance(run):
    async def main():
        # tenant-level `rule-processing: {mesh}` threads to the pool
        async with megabatch_runtime(
                tenants=("t0", "t1"), instance_id="mesh-t",
                rule_extra={"mesh": dict(MESH)}) as rt:
            pool = rt.api("rule-processing").engine("t0").pool_slot.pool
            assert pool.mesh is not None
            assert dict(pool.mesh.shape) == {"data": 4, "model": 2}
            assert rt.metrics.gauge("scoring.mesh_devices:zscore").value == 8
            # stacked params/rings shard the tenant axis over `model`
            # (replicated over `data`): the whole mesh carries state
            assert len(pool.ring.values.sharding.device_set) == 8
        # instance-level defaults reach tenants with no mesh override
        rt = ServiceRuntime(InstanceSettings(
            instance_id="mesh-i", scoring_mesh_data=4,
            scoring_mesh_model=2, scoring_megabatch=True))
        for cls in (DeviceManagementService, EventSourcesService,
                    InboundProcessingService, EventManagementService,
                    DeviceStateService, RuleProcessingService):
            rt.add_service(cls(rt))
        await rt.start()
        try:
            await rt.add_tenant(TenantConfig(
                tenant_id="solo", sections={"rule-processing": dict(RULE)}))
            eng = rt.api("rule-processing").engine("solo")
            assert eng.pool_slot is not None  # instance megabatch engaged
            assert dict(eng.pool_slot.pool.mesh.shape) == {"data": 4,
                                                           "model": 2}
        finally:
            await rt.stop()

    run(main())


# -- mesh on/off equivalence -------------------------------------------------

def test_mesh_on_off_score_equivalence(run):
    """The acceptance pair: a forced 8-device {data: 4, model: 2} mesh
    produces identical per-tenant scores, persisted telemetry, alerts,
    and committed offsets to the meshless stacked dispatch."""
    async def main():
        async with megabatch_runtime(instance_id="mesh-on",
                                     rule_extra={"mesh": dict(MESH)}) as rt:
            on = await _drive_tenants(rt)
            assert rt.metrics.gauge("scoring.mesh_devices:zscore").value == 8
            assert rt.metrics.counter(
                "scoring.megabatch_dispatches").value > 0
        async with megabatch_runtime(instance_id="mesh-off") as rt:
            off = await _drive_tenants(rt)
            assert rt.metrics.gauge("scoring.mesh_devices:zscore").value == 0
        for tid in TENANTS:
            scored_on, total_on, alerts_on, committed_on = on[tid]
            scored_off, total_off, alerts_off, committed_off = off[tid]
            assert total_on == total_off == 32 * 10
            assert scored_on.keys() == scored_off.keys()
            for key, val in scored_on.items():
                assert scored_off[key] == val, (tid, key)
            assert alerts_on == alerts_off and alerts_on
            assert committed_on == committed_off > 0

    run(main())


# -- hot-swap + add/remove under a sharded stack -----------------------------

def test_sharded_hot_swap_and_add_remove(run):
    """The lifecycle edge the mesh must survive: a donated param swap
    mid-flight keeps the dispatch's attribution (version fence), stack
    growth re-places shards, and a removed tenant's slot reuse leaks
    nothing — all with the tenant axis live on the `model` mesh axis."""
    async def main():
        metrics = MetricsRegistry()
        model = build_model("lstm", window=16, hidden=8)
        mesh = mesh_from_spec(MESH)
        pool = SharedScoringPool(
            model, metrics, PoolConfig(batch_buckets=(32,),
                                       batch_window_ms=50.0),
            mesh=mesh)
        got: dict[str, int] = {}

        def deliver_for(tid):
            async def deliver(scored):
                got[tid] = got.get(tid, 0) + len(scored)
            return deliver

        delivered: list = []

        async def capture(scored):
            delivered.append(scored)

        pool.register("a", TelemetryStore(history=32), 6.0, capture)
        pool.register("b", TelemetryStore(history=32), 6.0,
                      deliver_for("b"))
        await wait_until(lambda: pool.ready, timeout=120.0)
        # params live sharded: the stacked leaves span the mesh
        leaf = jax.tree.leaves(pool.stack.stacked)[0]
        assert len(leaf.sharding.device_set) == 8
        # dispatch, then swap mid-flight: the settled batch must carry
        # the DISPATCH-time version (the fence), sharded or not
        pool.admit("a", _batch("a"))
        pool._flush_round()
        v = pool.stack.set_params("a", model.init(jax.random.PRNGKey(7)))
        assert v == 1
        await wait_until(lambda: len(delivered) == 1, timeout=60.0)
        assert delivered[0].model_version == 0
        # the donated swap kept the placement
        leaf = jax.tree.leaves(pool.stack.stacked)[0]
        assert len(leaf.sharding.device_set) == 8
        # grow: a third tenant crosses the 2-capacity bucket → 4 rows
        # (model-axis multiples), re-placed, rebuild counted
        pool.register("c", TelemetryStore(history=32), 6.0,
                      deliver_for("c"))
        assert pool.stack.capacity == 4
        assert metrics.counter("scoring.stack_rebuilds").value >= 1
        leaf = jax.tree.leaves(pool.stack.stacked)[0]
        assert len(leaf.sharding.device_set) == 8
        await wait_until(lambda: pool.ready, timeout=120.0)
        # remove b (pending accounted dropped), the rest keep scoring
        pool.admit("b", _batch("b", t=20.0))
        pool.unregister("b")
        assert metrics.counter("scoring.admissions_dropped").value >= 8
        for tid in ("a", "c"):
            pool.admit(tid, _batch(tid, t=21.0))
        pool._flush_round()
        await wait_until(lambda: len(delivered) == 2
                         and got.get("c") == 8, timeout=60.0)
        assert delivered[1].model_version == 1  # post-swap attribution
        pool.close()

    run(main())


# -- adaptive megabatch window ----------------------------------------------

def _tuned_pool(window_auto=True):
    return SharedScoringPool(
        build_model("zscore", window=8), MetricsRegistry(),
        PoolConfig(batch_buckets=(32,), batch_window_ms=2.0,
                   window_auto=window_auto))


def _drive_tuner(pool, rounds, packed, live):
    """Simulate `rounds` flush rounds each packing `packed` tenants
    while the tenants in `live` keep admitting (the signal `admit`
    feeds the tuner)."""
    for _ in range(rounds):
        pool._tuner_tenants.update(live)
        pool._tune_window(packed)


def test_window_autotune_converges_and_never_flaps():
    pool = _tuned_pool()
    live = [f"t{i}" for i in range(8)]
    base = pool.cfg.window_s
    adjusts = pool.window_adjusts
    # chronically under-packed rounds (2 of 8 live tenants per
    # dispatch): the window widens to the 8× bound and STAYS there
    _drive_tuner(pool, 200, packed=2, live=live)
    assert pool._window_s == pytest.approx(base * pool.WINDOW_SPAN)
    at_bound = adjusts.value
    _drive_tuner(pool, 200, packed=2, live=live)
    assert adjusts.value == at_bound  # pinned, not flapping
    # full packs: narrows back to the configured floor and holds
    _drive_tuner(pool, 600, packed=8, live=live)
    assert pool._window_s == pytest.approx(base)
    at_floor = adjusts.value
    _drive_tuner(pool, 200, packed=8, live=live)
    assert adjusts.value == at_floor
    # the hysteresis band [0.5, 0.9]: mid occupancy moves nothing
    _drive_tuner(pool, 200, packed=6, live=live)  # 0.75 of 8
    assert adjusts.value == at_floor
    assert pool._window_s == pytest.approx(base)
    pool.close()


def test_window_autotune_off_pins_window():
    pool = _tuned_pool(window_auto=False)
    _drive_tuner(pool, 200, packed=1, live=[f"t{i}" for i in range(8)])
    assert pool._window_s == pool.cfg.window_s
    assert pool.window_adjusts.value == 0
    pool.close()


def test_window_autotune_idle_tenants_dont_pin_the_cap():
    """Registered-but-idle tenants must not drag occupancy down: a pool
    with 8 registered tenants where only ONE sends traffic holds the
    configured floor (a wider window could aggregate nothing), instead
    of ratcheting to 8× and taxing the lone active tenant's latency."""
    pool = _tuned_pool()
    pool.tenants = {f"t{i}": object() for i in range(8)}  # registered
    _drive_tuner(pool, 200, packed=1, live=["t0"])  # one live tenant
    assert pool._window_s == pool.cfg.window_s
    assert pool.window_adjusts.value == 0
    # several live tenants that never share a round DO earn a wider
    # window (1 of 3 packed = 0.33, under the 0.5 widen threshold)
    _drive_tuner(pool, 200, packed=1, live=["t0", "t1", "t2"])
    assert pool._window_s > pool.cfg.window_s
    pool.close()


# -- egress lane auto-tuner --------------------------------------------------

@contextlib.asynccontextmanager
async def autotune_runtime():
    rt = ServiceRuntime(InstanceSettings(instance_id="lane-at"))
    for cls in (DeviceManagementService, EventSourcesService,
                InboundProcessingService, EventManagementService,
                DeviceStateService, RuleProcessingService):
        rt.add_service(cls(rt))
    await rt.start()
    await rt.add_tenant(TenantConfig(tenant_id="t0", sections={
        "rule-processing": dict(RULE),
        "egress": {"autotune": True, "lanes": 1, "max_lanes": 4}}))
    eng = rt.api("rule-processing").engine("t0")
    sink = eng.session or eng.pool_slot
    await wait_until(lambda: sink.ready, timeout=60.0)
    try:
        yield rt, eng
    finally:
        await rt.stop()


def test_lane_autotune_scales_up_down_with_hysteresis(run):
    async def main():
        async with autotune_runtime() as (rt, eng):
            stage = eng.egress
            assert stage.lanes == 4 and stage.active == 1  # ceiling built
            stage.AUTOTUNE_COOLDOWN_S = 0.0  # the test drives beats fast
            # sustained backlog: 4 consecutive beats past half the shard
            # cap earn a lane — but the switch applies IDLE-ONLY (per-key
            # publish order), so it stays pending while backlogged
            stage.submitted += 40
            for _ in range(stage.AUTOTUNE_CONSECUTIVE):
                stage.autotune_observe(0.0, 0.1)
            assert stage.active == 1 and stage._pending_active == 2
            stage.accounted = stage.submitted  # drained → idle
            stage.autotune_observe(0.0, 0.1)
            assert stage.active == 2
            assert rt.metrics.counter("egress.autotune_adjusts").value == 1
            assert rt.metrics.gauge("egress.autotune_lanes:t0").value == 2
            # sustained loop lag with near-empty lanes sheds one (the
            # measured 1-core trade: idle lanes are dispatch-queue depth)
            for _ in range(stage.AUTOTUNE_CONSECUTIVE):
                stage.autotune_observe(0.2, 0.1)
            assert stage.active == 1
            # at the floor, lag alone can never push below 1 lane
            for _ in range(20):
                stage.autotune_observe(0.2, 0.1)
            assert stage.active == 1

    run(main())


def test_lane_autotune_never_flaps_on_spikes(run):
    async def main():
        async with autotune_runtime() as (rt, eng):
            stage = eng.egress
            stage.AUTOTUNE_COOLDOWN_S = 0.0
            # alternating one-beat spikes never reach the consecutive
            # bar: the lane count holds
            for _ in range(20):
                stage.submitted += 40          # spike
                stage.autotune_observe(0.0, 0.1)
                stage.accounted = stage.submitted  # drained
                stage.autotune_observe(0.0, 0.1)
            assert stage.active == 1
            assert rt.metrics.counter("egress.autotune_adjusts").value == 0
            # the TelemetryBeat actually drives the hook (wiring check)
            rt.beat.sample(loop_lag_s=0.0)
            assert stage.active == 1  # healthy beat: no decision

    run(main())


def test_lane_autotune_off_by_default(run):
    async def main():
        async with megabatch_runtime(tenants=("t0",),
                                     instance_id="lane-off") as rt:
            stage = rt.api("rule-processing").engine("t0").egress
            assert stage.lanes == 1  # no ceiling shards built
            stage.autotune_observe(0.5, 0.1)  # inert
            assert stage.active == 1
            assert rt.metrics.counter("egress.autotune_adjusts").value == 0

    run(main())


# -- the chaos seam ----------------------------------------------------------

def test_mesh_chaos_quarantines_with_provenance(run):
    """An injected `scoring.mesh` fault at admission dead-letters the
    admitting record (same contract as scoring.megabatch); the sharded
    pool survives and later records score normally."""
    async def main():
        from sitewhere_tpu.kernel.bus import TopicNaming
        from sitewhere_tpu.kernel.dlq import list_dead_letters
        from sitewhere_tpu.kernel.faults import FaultInjector

        fi = FaultInjector(seed=9)
        async with megabatch_runtime(tenants=("t0",), faults=fi,
                                     instance_id="mesh-ch",
                                     rule_extra={"mesh": dict(MESH)}) as rt:
            fi.arm("scoring.mesh", rate=1.0, max_faults=1)
            decoded = rt.naming.tenant_topic(
                "t0", TopicNaming.EVENT_SOURCE_DECODED)
            dlq = rt.naming.tenant_topic("t0", TopicNaming.DEAD_LETTER)
            await rt.bus.produce(decoded, _batch("t0", n=16, t=1000.0),
                                 key="gw")
            await wait_until(
                lambda: len(list_dead_letters(rt.bus, dlq)) >= 1,
                timeout=15.0)
            entries = list_dead_letters(rt.bus, dlq)
            assert entries[0][1]["original_topic"] == decoded
            # spent: the next record scores through the mesh normally
            scored_topic = rt.naming.tenant_topic(
                "t0", TopicNaming.SCORED_EVENTS)
            consumer = rt.bus.subscribe(scored_topic, group="mesh-ch-m")
            await rt.bus.produce(decoded, _batch("t0", n=16, t=1060.0),
                                 key="gw")
            seen: list = []

            def collect():
                seen.extend(consumer.poll_nowait(max_records=64))
                return sum(len(r.value) for r in seen) >= 16
            await wait_until(collect, timeout=15.0)
            consumer.close()

    run(main())
