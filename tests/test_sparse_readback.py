"""Sparse anomaly readback (ScoringConfig.readback="anomalies").

Device-side thresholding ships only anomalous (position, score) pairs
host-ward — the TPU-idiomatic answer to the measured D2H readback
ceiling (BASELINE.md). These tests pin: detection parity with full
readback, scratch/bucket-padding masking, duplicate-device rounds,
top-k overflow accounting, and the e2e alert path.
"""

import numpy as np

from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.kernel.metrics import MetricsRegistry
from sitewhere_tpu.models import build_model
from sitewhere_tpu.persistence.telemetry import TelemetryStore
from sitewhere_tpu.scoring.server import ScoringConfig, ScoringSession
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_pipeline import running_pipeline, wait_until
from tests.test_scoring import _fill_store


def _session(store, readback, sparse_k=0, buckets=(256,)):
    s = ScoringSession(
        build_model("lstm-stream", window=64), store, MetricsRegistry(),
        ScoringConfig(buckets=buckets, threshold=4.0, readback=readback,
                      sparse_k=sparse_k, seed=7))
    s.warmup()
    return s


def test_sparse_matches_full_readback(run):
    """Same anomaly set, same scores (fp16 tolerance), per flush —
    including flushes smaller than the bucket (padding masked)."""
    async def main():
        sim = DeviceSimulator(SimConfig(num_devices=200, seed=3),
                              tenant_id="t")
        store_a = TelemetryStore(history=128, initial_devices=200)
        _fill_store(store_a, sim, 70)
        sim2 = DeviceSimulator(SimConfig(num_devices=200, seed=3),
                               tenant_id="t")
        store_b = TelemetryStore(history=128, initial_devices=200)
        _fill_store(store_b, sim2, 70)

        full = _session(store_a, "full")
        sparse = _session(store_b, "anomalies")
        anomaly_cfg = SimConfig(num_devices=200, seed=3,
                                anomaly_rate=0.05, anomaly_magnitude=12.0)
        sim.cfg = anomaly_cfg
        sim2.cfg = anomaly_cfg
        for k in range(5):
            batch, _ = sim.tick(t=(70 + k) * 60.0)
            batch2, _ = sim2.tick(t=(70 + k) * 60.0)
            np.testing.assert_array_equal(batch.value, batch2.value)
            full.admit(batch)
            scored_f = await full.flush()
            sparse.admit(batch2)
            scored_s = await sparse.flush()

            f_anom = {int(d): float(s) for d, s in zip(
                scored_f.device_index[scored_f.is_anomaly],
                scored_f.score[scored_f.is_anomaly])}
            s_anom = {int(d): float(s) for d, s in zip(
                scored_s.device_index, scored_s.score)}
            assert set(s_anom) == set(f_anom)
            for d in f_anom:
                assert abs(s_anom[d] - f_anom[d]) <= \
                    2e-2 * max(1.0, abs(f_anom[d]))
            assert scored_s.is_anomaly.all()
            assert scored_s.total_scored == 200
            assert scored_f.total_scored == -1
        # every event was scored in both modes
        assert full.latency.count == sparse.latency.count == 1000
        full.close()
        sparse.close()

    run(main())


def test_sparse_duplicate_devices_rounds(run):
    """A flush carrying several events for one device scores each
    occurrence (rounds) and reports every anomalous one."""
    async def main():
        store = TelemetryStore(history=128, initial_devices=64)
        sim = DeviceSimulator(SimConfig(num_devices=64, seed=1),
                              tenant_id="t")
        _fill_store(store, sim, 70)
        s = _session(store, "anomalies")
        ctx = BatchContext(tenant_id="t", source="x")
        # device 5 gets two 100-sigma events in ONE flush; device 9 one
        dev = np.array([5, 9, 5], np.uint32)
        vals = np.array([1e4, 1e4, 1e4], np.float32)
        s.admit(MeasurementBatch(ctx, dev, np.zeros(3, np.uint16),
                                 vals, np.full(3, 4300.0)))
        scored = await s.flush()
        assert sorted(scored.device_index.tolist()) == [5, 5, 9]
        assert scored.is_anomaly.all() and (scored.score >= 4.0).all()
        s.close()

    run(main())


def test_sparse_topk_overflow_is_counted(run):
    """More anomalies than k slots: top-k report, overflow counter
    carries the remainder — never a silent truncation."""
    async def main():
        store = TelemetryStore(history=128, initial_devices=200)
        sim = DeviceSimulator(SimConfig(num_devices=200, seed=3),
                              tenant_id="t")
        _fill_store(store, sim, 70)
        s = _session(store, "anomalies", sparse_k=4)
        sim.cfg = SimConfig(num_devices=200, seed=3, anomaly_rate=1.0,
                            anomaly_magnitude=12.0)
        batch, _ = sim.tick(t=70 * 60.0)
        s.admit(batch)
        scored = await s.flush()
        assert len(scored) == 4                      # k slots
        assert s.anomaly_overflow.value > 0
        assert len(scored) + s.anomaly_overflow.value >= 150
        assert scored.total_scored == 200
        s.close()

    run(main())


def test_sparse_multichunk_flush_total_scored(run):
    """A sparse flush larger than the max bucket merges chunks with the
    TRUE scored count (-1 would claim full readback)."""
    async def main():
        store = TelemetryStore(history=128, initial_devices=600)
        sim = DeviceSimulator(SimConfig(num_devices=600, seed=2),
                              tenant_id="t")
        _fill_store(store, sim, 70)
        s = _session(store, "anomalies", buckets=(256,))
        sim.cfg = SimConfig(num_devices=600, seed=2, anomaly_rate=0.02,
                            anomaly_magnitude=12.0)
        batch, truth = sim.tick(t=70 * 60.0)
        s.admit(batch)
        scored = await s.flush()
        assert scored.total_scored == 600          # 3 chunks of ≤256
        assert set(np.nonzero(truth)[0]) <= set(
            scored.device_index.tolist())
        s.close()

    run(main())


def test_sparse_e2e_alert_parity(run):
    """Through the full pipeline, sparse readback emits the same
    model-anomaly alerts the full path does."""
    async def main():
        sections = {
            "event-management": {"history": 128},
            "rule-processing": {"model": "lstm-stream",
                                "model_config": {"window": 32},
                                "threshold": 4.0,
                                "batch_window_ms": 1.0,
                                "buckets": [256], "capacity": 256,
                                "readback": "anomalies"},
        }
        async with running_pipeline(num_devices=100,
                                    sections=sections) as rt:
            em = rt.api("event-management").management("acme")
            eng = rt.api("rule-processing").engine("acme")
            sim = DeviceSimulator(SimConfig(num_devices=100, seed=3),
                                  tenant_id="acme")
            for k in range(36):  # warm history through the store
                batch, _ = sim.tick(t=60.0 * k)
                em.telemetry.append_measurements(batch)
            await wait_until(lambda: eng.session.ready, timeout=60.0)
            eng.session.reload_history()
            sim.cfg = SimConfig(num_devices=100, seed=3,
                                anomaly_rate=0.1, anomaly_magnitude=12.0)
            receiver = rt.api("event-sources").engine("acme") \
                .receiver("default")
            batch, truth = sim.tick(t=60.0 * 40)
            await receiver.submit(batch.encode())
            await wait_until(
                lambda: len([a for a in em.list_alerts()
                             if a.source == "model"]) >= truth.sum(),
                timeout=20.0)
            model_alerts = [a for a in em.list_alerts()
                            if a.source == "model"]
            alert_devs = {em.dm.get_device(a.device_id).index
                          for a in model_alerts if a.device_id}
            assert set(np.nonzero(truth)[0]) <= alert_devs

    run(main())


def test_pool_sparse_matches_pool_full(run):
    """Pooled form (config 4): per-tenant thresholds ride as a device
    vector; sparse pool reports the same anomaly sets the full pool
    does — different alert bars per tenant respected."""
    async def main():
        import jax

        from sitewhere_tpu.scoring.pool import (
            PoolConfig,
            SharedScoringPool,
        )
        from tests.test_streaming import _make_pool_tenant

        model = build_model("lstm-stream", window=64)
        params = {tid: model.init(jax.random.PRNGKey(i + 10))
                  for i, tid in enumerate(("a", "b"))}
        pools = {}
        delivered = {"full": {}, "anomalies": {}}
        stores = {"full": {}, "anomalies": {}}
        sims = {"full": {}, "anomalies": {}}
        for mode in ("full", "anomalies"):
            pool = SharedScoringPool(
                model, MetricsRegistry(),
                PoolConfig(batch_buckets=(64,), batch_window_ms=1.0,
                           readback=mode))
            pools[mode] = pool
            for i, tid in enumerate(("a", "b")):
                # tenant b gets a stricter bar than tenant a
                stores[mode][tid], sims[mode][tid], _ = _make_pool_tenant(
                    pool, tid, 30, i + 20, delivered[mode],
                    params=params[tid],
                    threshold=4.0 if tid == "a" else 6.0)
            await wait_until(lambda p=pool: p.ready, timeout=60.0)

        anomaly = dict(anomaly_rate=0.1, anomaly_magnitude=12.0)
        for k in range(3):
            for mode in ("full", "anomalies"):
                for i, tid in enumerate(("a", "b")):
                    sims[mode][tid].cfg = SimConfig(
                        num_devices=30, seed=i + 20, **anomaly)
                    batch, _ = sims[mode][tid].tick(t=(70 + k) * 60.0)
                    stores[mode][tid].append_measurements(batch)
                    pools[mode].admit(tid, batch)
            await wait_until(
                lambda k=k: all(len(delivered[m][t]) >= k + 1
                                for m in ("full", "anomalies")
                                for t in ("a", "b")), timeout=30.0)
            for tid in ("a", "b"):
                got_f = delivered["full"][tid][k]
                got_s = delivered["anomalies"][tid][k]
                f_anom = {int(d): float(s) for d, s in zip(
                    got_f.device_index[got_f.is_anomaly],
                    got_f.score[got_f.is_anomaly])}
                s_anom = {int(d): float(s) for d, s in zip(
                    got_s.device_index, got_s.score)}
                assert set(s_anom) == set(f_anom), (tid, k)
                for d in f_anom:
                    assert abs(s_anom[d] - f_anom[d]) <= 2e-2 * max(
                        1.0, abs(f_anom[d]))
                assert got_s.total_scored == 30
        for pool in pools.values():
            pool.close()

    run(main())
