"""Streaming scorer tests: the event-native hot path (models/lstm.py
StreamingLstmModel + scoring/stream.py StreamingRing) that replaces the
per-event window rescan — ONE cell step per event on resident state.
This is the benchmark's default model; its behavior is pinned here:
detection parity with the windowed scorer, state regrow, fault
recovery, and the checkpoint-rollout reseed."""

import asyncio

import numpy as np

from sitewhere_tpu.kernel.metrics import MetricsRegistry
from sitewhere_tpu.models import build_model
from sitewhere_tpu.persistence.telemetry import TelemetryStore
from sitewhere_tpu.scoring.pool import PoolConfig, SharedScoringPool
from sitewhere_tpu.scoring.server import ScoringConfig, ScoringSession
from sitewhere_tpu.scoring.stream import StackedStreamingRing, StreamingRing
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_pipeline import wait_until
from tests.test_scoring import _fill_store


def _session(store, buckets=(256,), threshold=4.0, window=64):
    s = ScoringSession(
        build_model("lstm-stream", window=window), store, MetricsRegistry(),
        ScoringConfig(buckets=buckets, threshold=threshold))
    s.warmup()
    return s


def test_streaming_detects_injected_anomalies(run):
    """Same detection bar the windowed scorer passes: 12-sigma spikes
    separate cleanly through the one-step-per-event hot path."""

    async def main():
        store = TelemetryStore(history=128, initial_devices=200)
        sim = DeviceSimulator(SimConfig(num_devices=200, seed=3), tenant_id="t")
        _fill_store(store, sim, 70)
        s = _session(store)
        assert isinstance(s.ring, StreamingRing)
        sim.cfg = SimConfig(num_devices=200, seed=3, anomaly_rate=0.05,
                            anomaly_magnitude=12.0)
        hits, truths = [], []
        for k in range(5):
            batch, truth = sim.tick(t=(70 + k) * 60.0)
            store.append_measurements(batch)
            s.admit(batch)
            scored = await s.flush()
            hits.append(scored.is_anomaly)
            truths.append(truth)
        det, tr = np.concatenate(hits), np.concatenate(truths)
        assert (det == tr).mean() > 0.97
        assert det[tr].mean() > 0.9
        s.close()

    run(main())


def test_streaming_matches_windowed_on_warm_history(run):
    """First post-warmup flush: streaming scores (state seeded by window
    replay) agree with the windowed model's scores to within the
    documented normalization drift — same weights, same events."""

    async def main():
        store = TelemetryStore(history=128, initial_devices=100)
        sim = DeviceSimulator(SimConfig(num_devices=100, seed=7), tenant_id="t")
        _fill_store(store, sim, 70)
        stream = _session(store, threshold=4.0)
        windowed = ScoringSession(
            build_model("lstm", window=64), store, MetricsRegistry(),
            ScoringConfig(buckets=(256,), threshold=4.0))
        windowed.warmup()
        # same params: streaming shares the windowed param format
        windowed.params = stream.params
        batch, _ = sim.tick(t=70 * 60.0)
        store.append_measurements(batch)
        for s in (stream, windowed):
            s.admit(batch)
        a = await stream.flush()
        b = await windowed.flush()
        # warm_state replays the very window the windowed model scans, so
        # the standing predictions coincide; normalization frames differ
        # by one step of Welford drift
        np.testing.assert_allclose(a.score, b.score, atol=0.15)
        stream.close()
        windowed.close()

    run(main())


def test_streaming_regrow_preserves_state(run):
    """A device index past capacity triggers regrow; old devices' state
    survives and new devices score once they accrue history."""

    async def main():
        store = TelemetryStore(history=64, initial_devices=100)
        sim = DeviceSimulator(SimConfig(num_devices=100, seed=1), tenant_id="t")
        _fill_store(store, sim, 40)
        s = _session(store, buckets=(128,), window=32)
        cap0 = s.ring.capacity
        s.ring.ensure_capacity(cap0 + 10)
        assert s.ring.capacity > cap0
        # old rows kept their history count; fresh rows start cold
        counts = np.asarray(s.ring.state["count"])
        assert counts[:100].min() >= 8
        assert counts[cap0:cap0 + 5].max() == 0
        # still scores after the regrow
        batch, _ = sim.tick(t=41 * 60.0)
        s.admit(batch)
        scored = await s.flush()
        assert scored.score.shape[0] == 100
        s.close()

    run(main())


def test_streaming_fault_recovery_reloads_from_host(run):
    """A faulted ring (donated state lost) recovers by replaying host
    windows — same story as the window ring."""

    async def main():
        store = TelemetryStore(history=64, initial_devices=50)
        sim = DeviceSimulator(SimConfig(num_devices=50, seed=2), tenant_id="t")
        _fill_store(store, sim, 40)
        s = _session(store, buckets=(64,), window=32)
        s.ring.faulted = True
        s._recover_ring()
        assert not s.ring.faulted
        assert np.asarray(s.ring.state["count"])[:50].min() >= 8
        batch, _ = sim.tick(t=41 * 60.0)
        s.admit(batch)
        scored = await s.flush()
        assert scored.score.shape[0] == 50
        s.close()

    run(main())


def test_streaming_swap_params_reseeds_state(run):
    """Code-review regression: a checkpoint rollout must reseed the
    resident streaming state under the NEW weights — stale h/c/pred from
    the old weights mis-scores until it washes out."""

    async def main():
        import jax

        store = TelemetryStore(history=128, initial_devices=50)
        sim = DeviceSimulator(SimConfig(num_devices=50, seed=5), tenant_id="t")
        _fill_store(store, sim, 70)
        s = _session(store)
        old_pred = np.asarray(s.ring.state["pred"][:50]).copy()
        new_params = s.model.init(jax.random.PRNGKey(99))
        s.swap_params(new_params)
        # reference: a session born with the new weights (identical
        # seeding path) — the swapped session must match it, not the
        # stale old-weight state
        fresh = ScoringSession(
            build_model("lstm-stream", window=64), store, MetricsRegistry(),
            ScoringConfig(buckets=(256,)), params=new_params)
        fresh.warmup()
        np.testing.assert_allclose(np.asarray(s.ring.state["pred"][:50]),
                                   np.asarray(fresh.ring.state["pred"][:50]),
                                   atol=1e-5)
        # and it genuinely changed (old state would have been wrong)
        assert np.abs(np.asarray(s.ring.state["pred"][:50])
                      - old_pred).max() > 1e-3
        assert s.version == 1
        s.close()
        fresh.close()

    run(main())


# -- pooled streaming (config 4 at streaming speed) -------------------------


def _make_pool_tenant(pool, tid, n_devices, seed, delivered, params=None,
                      threshold=4.0, ticks=70):
    store = TelemetryStore(history=128, initial_devices=n_devices)
    sim = DeviceSimulator(SimConfig(num_devices=n_devices, seed=seed),
                          tenant_id=tid)
    _fill_store(store, sim, ticks)
    delivered[tid] = []

    async def deliver(scored, tid=tid):
        delivered[tid].append(scored)

    slot = pool.register(tid, store, threshold, deliver, params=params)
    return store, sim, slot


def test_pool_streaming_uses_stacked_streaming_ring(run):
    """A streaming model in the shared pool gets the streaming stacked
    ring (one cell step per event), not the windowed W-step rescan."""

    async def main():
        model = build_model("lstm-stream", window=64)
        pool = SharedScoringPool(model, MetricsRegistry(),
                                 PoolConfig(batch_buckets=(64,),
                                            batch_window_ms=1.0))
        delivered: dict[str, list] = {}
        _make_pool_tenant(pool, "a", 20, 3, delivered)
        assert isinstance(pool.ring, StackedStreamingRing)
        await wait_until(lambda: pool.ready, timeout=60.0)
        assert np.asarray(pool.ring.state["count"])[0, :20].min() >= 8
        pool.close()

    run(main())


def test_pool_streaming_matches_dedicated_sessions(run):
    """Parity: N tenants scored through the shared streaming pool get
    the SAME scores as each tenant alone in a dedicated streaming
    session — same weights, same events, same seeding path."""

    async def main():
        import jax

        model = build_model("lstm-stream", window=64)
        params = {tid: model.init(jax.random.PRNGKey(i + 10))
                  for i, tid in enumerate(("a", "b"))}
        pool = SharedScoringPool(model, MetricsRegistry(),
                                 PoolConfig(batch_buckets=(64,),
                                            batch_window_ms=1.0))
        delivered: dict[str, list] = {}
        stores, sims = {}, {}
        for i, tid in enumerate(("a", "b")):
            stores[tid], sims[tid], _ = _make_pool_tenant(
                pool, tid, 30, i + 20, delivered, params=params[tid])
        await wait_until(lambda: pool.ready, timeout=60.0)

        # dedicated reference sessions share the host stores (already
        # seeded) and the exact params
        refs = {}
        for tid in ("a", "b"):
            refs[tid] = ScoringSession(
                build_model("lstm-stream", window=64), stores[tid],
                MetricsRegistry(), ScoringConfig(buckets=(64,)),
                params=params[tid])
            refs[tid].warmup()

        for k in range(3):
            expect = {}
            for tid in ("a", "b"):
                batch, _ = sims[tid].tick(t=(70 + k) * 60.0)
                stores[tid].append_measurements(batch)
                pool.admit(tid, batch)
                refs[tid].admit(batch)
                expect[tid] = await refs[tid].flush()
            await wait_until(
                lambda k=k: all(len(delivered[t]) == k + 1
                                for t in ("a", "b")), timeout=30.0)
            for tid in ("a", "b"):
                got = delivered[tid][k]
                order = np.argsort(got.device_index)
                ref_order = np.argsort(expect[tid].device_index)
                # pooled (vmap over the stack) vs dedicated flushes round
                # to fp16 independently at readback (score_dtype default):
                # one fp16 ulp at z≈8 is ~0.008, so parity holds to ~2e-2
                np.testing.assert_allclose(
                    got.score[order], expect[tid].score[ref_order],
                    atol=2e-2)
        for r in refs.values():
            r.close()
        pool.close()

    run(main())


def test_pool_streaming_swap_params_reseeds_slot(run):
    """Checkpoint rollout on ONE pooled tenant reseeds only that
    tenant's streaming state under the new weights; neighbors keep
    their state untouched."""

    async def main():
        import jax

        model = build_model("lstm-stream", window=64)
        pool = SharedScoringPool(model, MetricsRegistry(),
                                 PoolConfig(batch_buckets=(64,),
                                            batch_window_ms=1.0))
        delivered: dict[str, list] = {}
        stores, slots = {}, {}
        for i, tid in enumerate(("a", "b")):
            stores[tid], _, slots[tid] = _make_pool_tenant(
                pool, tid, 25, i + 30, delivered)
        await wait_until(lambda: pool.ready, timeout=60.0)
        slot_a = pool.stack.slots["a"]
        slot_b = pool.stack.slots["b"]
        pred_a0 = np.asarray(pool.ring.state["pred"][slot_a, :25]).copy()
        pred_b0 = np.asarray(pool.ring.state["pred"][slot_b, :25]).copy()

        new_params = model.init(jax.random.PRNGKey(99))
        version = slots["a"].swap_params(new_params)
        assert version == 1
        # a's state moved to the new weights...
        pred_a1 = np.asarray(pool.ring.state["pred"][slot_a, :25])
        assert np.abs(pred_a1 - pred_a0).max() > 1e-3
        # ...and matches a dedicated session born with them
        ref = ScoringSession(
            build_model("lstm-stream", window=64), stores["a"],
            MetricsRegistry(), ScoringConfig(buckets=(64,)),
            params=new_params)
        ref.warmup()
        np.testing.assert_allclose(
            pred_a1, np.asarray(ref.ring.state["pred"][:25]), atol=1e-5)
        # b untouched
        np.testing.assert_allclose(
            np.asarray(pool.ring.state["pred"][slot_b, :25]), pred_b0,
            atol=0.0)
        ref.close()
        pool.close()

    run(main())
