"""utils/http.py: the shared dependency-free HTTP client (webhook
connector + HTTP command-delivery provider both ride it)."""

import asyncio

import pytest

from sitewhere_tpu.utils.http import (
    http_post,
    http_post_retrying,
    parse_http_url,
)


def test_parse_http_url():
    assert parse_http_url("http://gw:8080/a/b?x=1") == \
        ("gw", 8080, "/a/b?x=1")
    assert parse_http_url("http://gw") == ("gw", 80, "/")
    with pytest.raises(ValueError, match="http:// only"):
        parse_http_url("https://gw/secure")
    with pytest.raises(ValueError):
        parse_http_url("ftp://gw/x")


async def _server(handler):
    srv = await asyncio.start_server(handler, "127.0.0.1", 0)
    return srv, srv.sockets[0].getsockname()[1]


def test_post_and_status(run):
    async def main():
        seen = []

        async def handler(reader, writer):
            req = await reader.readuntil(b"\r\n\r\n")
            n = int([ln for ln in req.split(b"\r\n")
                     if ln.lower().startswith(b"content-length")][0]
                    .split(b":")[1])
            seen.append((req, await reader.readexactly(n)))
            writer.write(b"HTTP/1.1 201 Created\r\nContent-Length: 0"
                         b"\r\n\r\n")
            await writer.drain()
            writer.close()

        srv, port = await _server(handler)
        status = await http_post("127.0.0.1", port, "/x", b"body-bytes",
                                 content_type="application/octet-stream")
        assert status == 201
        req, body = seen[0]
        assert body == b"body-bytes"
        assert b"Content-Type: application/octet-stream" in req
        srv.close()
        await srv.wait_closed()

    run(main())


def test_post_timeout_on_stalled_endpoint(run):
    """An endpoint that accepts but never answers must not wedge the
    caller past timeout_s (ONE bound over connect+write+read)."""
    async def main():
        stall = asyncio.Event()

        async def handler(reader, writer):
            try:
                await stall.wait()
            finally:
                writer.close()

        srv, port = await _server(handler)
        with pytest.raises(asyncio.TimeoutError):
            await http_post("127.0.0.1", port, "/", b"x", timeout_s=0.3)
        stall.set()  # release the handler: 3.12 wait_closed() waits for it
        srv.close()
        await srv.wait_closed()

    run(main())


def test_retrying_backoff_and_accounting(run):
    async def main():
        codes = [500, 503, 200]
        hits = []

        async def handler(reader, writer):
            await reader.readuntil(b"\r\n\r\n")
            code = codes[min(len(hits), len(codes) - 1)]
            hits.append(code)
            writer.write(f"HTTP/1.1 {code} X\r\nContent-Length: 0"
                         f"\r\n\r\n".encode())
            await writer.drain()
            writer.close()

        srv, port = await _server(handler)
        ok, last = await http_post_retrying("127.0.0.1", port, "/", b"x",
                                            retries=3, backoff_s=0.01)
        assert ok and last is None and hits == [500, 503, 200]

        # exhausted retries: delivered False, last error carries status
        hits.clear()
        codes[:] = [500]
        ok, last = await http_post_retrying("127.0.0.1", port, "/", b"x",
                                            retries=2, backoff_s=0.01)
        assert not ok and "HTTP 500" in str(last) and len(hits) == 2

        # connection refused: OSError surfaced as last error
        srv.close()
        await srv.wait_closed()
        ok, last = await http_post_retrying("127.0.0.1", port, "/", b"x",
                                            retries=2, backoff_s=0.01)
        assert not ok and isinstance(last, OSError)

    run(main())
