"""Flow-control subsystem tests (kernel/flow.py, ISSUE 2).

Token-bucket conformance under a fake clock, DRR fairness, shed-policy
transitions, REST 429 + Retry-After, Kafka Produce throttle-time, the
shed routing inside rule-processing, and DLQ replay passing through
flow control like live traffic.
"""

import asyncio
import struct

import numpy as np

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.kernel.bus import EventBus, TopicNaming
from sitewhere_tpu.kernel.flow import (
    DegradedZscore,
    DrrScheduler,
    FlowController,
    OverloadController,
    TokenBucket,
)

from tests.test_pipeline import running_pipeline, wait_until


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- token bucket ------------------------------------------------------------

def test_token_bucket_rate_and_burst():
    clock = FakeClock()
    b = TokenBucket(rate=100.0, burst=10.0, clock=clock)
    # burst: 10 immediate acquisitions, the 11th is refused
    for _ in range(10):
        assert b.try_acquire(1)
    assert not b.try_acquire(1)
    # retry_after names the exact refill horizon for 1 token at 100/s
    assert abs(b.retry_after(1) - 0.01) < 1e-9
    # refill is rate-proportional...
    clock.advance(0.05)
    for _ in range(5):
        assert b.try_acquire(1)
    assert not b.try_acquire(1)
    # ...and capped at burst after a long idle
    clock.advance(100.0)
    assert b.tokens == 10.0
    assert b.try_acquire(10) and not b.try_acquire(1)


def test_token_bucket_bulk_and_conformance():
    """Sustained draw at exactly the configured rate always admits;
    rate + epsilon eventually refuses — the ±burst conformance bound."""
    clock = FakeClock()
    b = TokenBucket(rate=1000.0, burst=50.0, clock=clock)
    admitted = 0
    for _ in range(200):           # offer 2000 ev/s for 1 s in 5 ms steps
        clock.advance(0.005)
        if b.try_acquire(10):
            admitted += 10
    # admitted ≤ rate × horizon + burst, and ≥ rate × horizon − one draw
    assert 990 <= admitted <= 1050


# -- deficit round robin -----------------------------------------------------

def test_drr_equal_weights_10_to_1_offered_load():
    s = DrrScheduler(quantum=1.0)
    for i in range(2000):
        s.enqueue("hog", ("hog", i))
    for i in range(200):
        s.enqueue("meek", ("meek", i))
    drained = s.drain(max_entries=400)
    shares = {"hog": 0, "meek": 0}
    for lane, _payload, _cost in drained:
        shares[lane] += 1
    # equal weights → equal drained shares despite 10:1 offered load
    assert abs(shares["hog"] - shares["meek"]) <= 0.1 * 400


def test_drr_weighted_shares():
    s = DrrScheduler(quantum=1.0)
    s.lane_weight("big", 3.0)
    s.lane_weight("small", 1.0)
    for i in range(1000):
        s.enqueue("big", i)
        s.enqueue("small", i)
    drained = s.drain(max_entries=400)
    big = sum(1 for lane, *_ in drained if lane == "big")
    assert abs(big / 400 - 0.75) <= 0.1


def test_drr_drains_everything():
    s = DrrScheduler()
    s.enqueue("a", 1, cost=5.0)      # cost above quantum: needs passes
    s.enqueue("b", 2)
    assert {p for _, p, _ in s.drain()} == {1, 2}
    assert s.pending == 0 and s.take() is None


# -- shed-policy state machine ----------------------------------------------

def test_shed_policy_transitions_reject_degrade_defer():
    c = OverloadController(reject_at=0.5, degrade_at=0.75, defer_at=0.9,
                           hysteresis=0.8)
    assert c.mode == "ok"
    assert c.update(0.3) == "ok"
    assert c.update(0.55) == "reject"
    assert c.update(0.8) == "degrade"
    assert c.update(0.95) == "defer"
    # hysteresis: 0.85 ≥ 0.9 × 0.8 → still defer (no flap at the edge)
    assert c.update(0.85) == "defer"
    # below 0.72 → de-escalates to whatever the pressure names (reject)
    assert c.update(0.6) == "reject"
    # below 0.5 × 0.8 → fully recovered
    assert c.update(0.3) == "ok"


def test_flow_controller_overload_gates_ingress():
    fc = FlowController(InstanceSettings(), clock=FakeClock())
    fc.set_quota("t", rate=1000.0, burst=100.0)
    assert fc.admit_ingress("t", 10).admitted
    fc.force_mode("t", "reject")
    d = fc.admit_ingress("t", 10)
    assert not d.admitted and d.reason == "overload:reject"
    fc.force_mode("t", "ok")
    assert fc.admit_ingress("t", 10).admitted


def test_report_scorer_drives_mode():
    fc = FlowController(InstanceSettings(), clock=FakeClock())
    fc.set_quota("t", rate=0.0)
    assert fc.report_scorer("t", pending=100, cap=1000) == "ok"
    assert fc.report_scorer("t", pending=800, cap=1000) == "degrade"
    assert fc.report_scorer("t", pending=980, cap=1000) == "defer"
    assert fc.report_scorer("t", pending=0, cap=1000) == "ok"


# -- degraded fallback scorer ------------------------------------------------

def test_degraded_zscore_flags_spikes():
    dz = DegradedZscore()
    dev = np.arange(64, dtype=np.uint32)
    rng = np.random.default_rng(0)
    for _ in range(50):
        dz.score(dev, rng.normal(20.0, 0.5, 64).astype(np.float32))
    vals = rng.normal(20.0, 0.5, 64).astype(np.float32)
    vals[7] = 60.0
    z = dz.score(dev, vals)
    assert z[7] > 10.0
    assert np.median(z[np.arange(64) != 7]) < 3.0


# -- weighted-fair inbound admission ----------------------------------------

def test_admit_fair_uncapped_is_passthrough(run):
    async def main():
        fc = FlowController(InstanceSettings())   # flow_inbound_rate = 0
        await asyncio.wait_for(fc.admit_fair("t", 1000.0), 1.0)

    run(main())


def test_admit_fair_capped_grants_all(run):
    async def main():
        # offered (120 × 2048) exceeds burst (2 × rate): the tail queues
        # in DRR lanes and every waiter must still be granted (liveness
        # under contention; fairness itself is pinned by the DRR tests)
        fc = FlowController(InstanceSettings(flow_inbound_rate=100_000.0))
        waits = [fc.admit_fair(tid, 2048.0)
                 for tid in ("a", "b") for _ in range(60)]
        await asyncio.wait_for(asyncio.gather(*waits), 15.0)

    run(main())


# -- rule-processing shed routing (end-to-end) -------------------------------

def _enriched_batch(n=32, t=5000.0):
    return MeasurementBatch(
        BatchContext(tenant_id="acme", source="test"),
        np.arange(n, dtype=np.uint32), np.zeros(n, np.uint16),
        np.full(n, 21.0, np.float32), np.full(n, t))


_RULE_SECTIONS = {"rule-processing": {
    "model": "zscore", "model_config": {"window": 16},
    "threshold": 6.0, "batch_window_ms": 1.0, "buckets": [256]}}


def test_defer_mode_spools_then_replays(run):
    async def main():
        async with running_pipeline(num_devices=32,
                                    sections=_RULE_SECTIONS) as rt:
            session = rt.api("rule-processing").engine("acme").session
            await wait_until(lambda: session.ready)
            enriched = rt.naming.tenant_topic(
                "acme", TopicNaming.OUTBOUND_ENRICHED)
            deferred = rt.naming.tenant_topic(
                "acme", TopicNaming.DEFERRED_EVENTS)
            # overload ingress gate: any shed mode rejects new publishes
            receiver = rt.api("event-sources").engine("acme") \
                .receiver("default")
            from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

            sim = DeviceSimulator(SimConfig(num_devices=32),
                                  tenant_id="acme")
            rt.flow.force_mode("acme", "defer")
            assert not await receiver.submit(sim.payload(t=100.0)[0])
            # traffic already inside the pipeline is spooled, not scored:
            # feed the scorer's consumer directly while defer is pinned
            for k in range(2):
                await rt.bus.produce(enriched,
                                     _enriched_batch(t=5000.0 + k))
            await wait_until(
                lambda: sum(len(r.value) for r in rt.bus.peek(
                    deferred, limit=100)) >= 64)
            snap = rt.metrics.snapshot()
            assert snap.get("flow.shed_defer:acme", 0) >= 64
            assert session.scored_meter.rate(60.0) == 0.0  # nothing scored
            # overload clears → the spool drains back through the scorer
            rt.flow.force_mode("acme", "ok")
            await wait_until(
                lambda: rt.metrics.snapshot().get(
                    "flow.deferred_replayed:acme", 0) >= 64, timeout=15.0)
            await wait_until(lambda: session.latency.count >= 64,
                             timeout=15.0)

    run(main())


def test_degrade_mode_scores_via_fallback(run):
    async def main():
        async with running_pipeline(num_devices=32,
                                    sections=_RULE_SECTIONS) as rt:
            session = rt.api("rule-processing").engine("acme").session
            await wait_until(lambda: session.ready)
            enriched = rt.naming.tenant_topic(
                "acme", TopicNaming.OUTBOUND_ENRICHED)
            scored_topic = rt.naming.tenant_topic(
                "acme", TopicNaming.SCORED_EVENTS)
            consumer = rt.bus.subscribe(scored_topic, group="t.flowdeg")
            rt.flow.force_mode("acme", "degrade")
            await rt.bus.produce(enriched, _enriched_batch())
            scored = []

            def got_fallback():
                scored.extend(r.value
                              for r in consumer.poll_nowait(max_records=64))
                # model_version -1 marks the degraded fallback scorer
                return any(b.model_version == -1 for b in scored)

            await wait_until(got_fallback)
            snap = rt.metrics.snapshot()
            assert snap.get("flow.shed_degrade:acme", 0) >= 32
            consumer.close()

    run(main())


# -- REST: 429 + Retry-After -------------------------------------------------

def test_rest_ingest_429_retry_after(run):
    from tests.test_rest import http, rest_instance

    async def main():
        async with rest_instance() as (rt, port):
            _, body = await http(port, "POST", "/api/jwt",
                                 basic="admin:password")
            token = body["token"]
            await http(port, "POST", "/api/tenants", token=token,
                       body={"token": "acme", "sections": {
                           "flow": {"rate": 0.1, "burst": 2.0}}})
            await http(port, "POST", "/api/devicetypes", token=token,
                       tenant="acme", body={"token": "dt", "name": "T"})
            await http(port, "POST", "/api/devices", token=token,
                       tenant="acme", body={"token": "d1",
                                            "deviceType": "dt"})
            # burst 2 admits two, the third answers 429 + Retry-After
            statuses = []
            for _ in range(3):
                status, headers, data = await http(
                    port, "POST", "/api/assignments/d1-a/measurements",
                    token=token, tenant="acme",
                    body={"mtype": 0, "value": 1.0}, raw=True)
                statuses.append((status, headers))
            assert [s for s, _ in statuses[:2]] == [200, 200]
            status, headers = statuses[2]
            assert status == 429
            assert int(headers["retry-after"]) >= 1
            # quota surface reflects the live state
            status, body = await http(port, "GET",
                                      "/api/tenants/acme/quota",
                                      token=token)
            assert status == 200 and body["rate"] == 0.1
            assert body["rejected"] >= 1
            # runtime update opens the gate without an engine respin
            status, body = await http(port, "PUT",
                                      "/api/tenants/acme/quota",
                                      token=token, body={"rate": 10000.0})
            assert status == 200 and body["rate"] == 10000.0
            status, _ = await http(
                port, "POST", "/api/assignments/d1-a/measurements",
                token=token, tenant="acme", body={"mtype": 0, "value": 1.0})
            assert status == 200

    run(main())


# -- Kafka: Produce v1 throttle-time ----------------------------------------

def _s(v):
    b = v.encode()
    return struct.pack(">h", len(b)) + b


async def _kafka_produce_v1(host, port, topic, n_msgs):
    """Minimal Produce v1 (body identical to v0; response appends
    throttle_time_ms). Returns (error_code, base_offset, throttle_ms)."""
    from sitewhere_tpu.kernel.kafka_endpoint import encode_message_set

    reader, writer = await asyncio.open_connection(host, port)
    try:
        mset = encode_message_set(
            [(i, None, b"x" * 8, 0) for i in range(n_msgs)])
        body = (struct.pack(">hi", 1, 1000)        # acks=1, timeout
                + struct.pack(">i", 1) + _s(topic)
                + struct.pack(">i", 1) + struct.pack(">i", 0)
                + struct.pack(">i", len(mset)) + mset)
        req = struct.pack(">hhi", 0, 1, 77) + _s("flow-test") + body
        writer.write(struct.pack(">i", len(req)) + req)
        await writer.drain()
        size = struct.unpack(">i", await reader.readexactly(4))[0]
        payload = memoryview(await reader.readexactly(size))
        corr = struct.unpack_from(">i", payload, 0)[0]
        assert corr == 77
        off = 4
        n_topics = struct.unpack_from(">i", payload, off)[0]
        off += 4
        assert n_topics == 1
        name_len = struct.unpack_from(">h", payload, off)[0]
        off += 2 + name_len
        n_parts = struct.unpack_from(">i", payload, off)[0]
        off += 4
        assert n_parts == 1
        _pid, err, base = struct.unpack_from(">ihq", payload, off)
        off += 14
        throttle_ms = struct.unpack_from(">i", payload, off)[0]
        return err, base, throttle_ms
    finally:
        writer.close()


def test_kafka_produce_v1_throttle_time(run):
    from sitewhere_tpu.kernel.kafka_endpoint import KafkaEndpoint

    async def main():
        bus = EventBus(default_partitions=1)
        naming = TopicNaming("flowk")
        fc = FlowController(InstanceSettings())
        fc.set_quota("t1", rate=10.0, burst=5.0)
        ep = KafkaEndpoint(bus, flow=fc, naming=naming)
        await ep.start()
        try:
            topic = naming.tenant_topic("t1", "event-source-decoded-events")
            # within burst: no throttle
            err, base, throttle = await _kafka_produce_v1(
                "127.0.0.1", ep.port, topic, 3)
            assert err == 0 and throttle == 0
            # over quota: records still accepted (Kafka quota semantics)
            # but the response carries a positive throttle hint
            err, base2, throttle = await _kafka_produce_v1(
                "127.0.0.1", ep.port, topic, 40)
            assert err == 0 and throttle > 0
            assert bus._topics[topic].partitions[0].end_offset == 43
            # a non-tenant topic is never throttled
            err, _, throttle = await _kafka_produce_v1(
                "127.0.0.1", ep.port, "plain-topic", 40)
            assert err == 0 and throttle == 0
        finally:
            await ep.stop()

    run(main())


# -- DLQ replay passes through flow control ----------------------------------

def _mk_batch(n=1):
    return MeasurementBatch(
        BatchContext(tenant_id="t", source="test"),
        np.arange(n, dtype=np.uint32), np.zeros(n, np.uint16),
        np.ones(n, np.float32), np.full(n, 1000.0))


def test_dlq_replay_respects_quota(run):
    from sitewhere_tpu.kernel.dlq import quarantine, replay_dead_letters

    async def main():
        bus = EventBus(default_partitions=1)
        clock = FakeClock()
        fc = FlowController(InstanceSettings(), clock=clock)
        fc.set_quota("t", rate=1.0, burst=2.0)
        src_topic, dlq_topic = "src", "t.dlq"
        for _ in range(5):
            await bus.produce(src_topic, _mk_batch(1))
        consumer = bus.subscribe(src_topic, group="g")
        for rec in await consumer.poll(max_records=5, timeout=0.5):
            await quarantine(bus, dlq_topic, rec,
                             ValueError("poison"), "test")
        consumer.commit()
        # burst 2 → replay admits exactly 2, then pauses over quota
        n = await replay_dead_letters(bus, dlq_topic, flow=fc, tenant_id="t")
        assert n == 2
        # nothing refilled: a second call replays nothing more
        assert await replay_dead_letters(bus, dlq_topic, flow=fc,
                                         tenant_id="t") == 0
        # quota refills → the SAME records resume (no duplicates, no loss)
        clock.advance(10.0)
        assert await replay_dead_letters(bus, dlq_topic, flow=fc,
                                         tenant_id="t") == 2
        clock.advance(10.0)
        assert await replay_dead_letters(bus, dlq_topic, flow=fc,
                                         tenant_id="t") == 1
        end = bus._topics[src_topic].partitions[0].end_offset
        assert end == 10    # 5 originals + 5 replayed exactly once

    run(main())


# -- chaos seams -------------------------------------------------------------

def test_flow_fault_sites_armed():
    from sitewhere_tpu.kernel.faults import FaultInjected, FaultInjector

    fc = FlowController(InstanceSettings())
    fc.faults = FaultInjector(seed=1).arm("flow.admit", rate=1.0,
                                          max_faults=1)
    try:
        fc.admit_ingress("t", 1)
        raise AssertionError("flow.admit fault did not fire")
    except FaultInjected:
        pass
    assert fc.admit_ingress("t", 1).admitted   # bounded: next call is clean
    fc.faults.arm("flow.shed", rate=1.0, max_faults=1)
    try:
        fc.shed_mode("t")
        raise AssertionError("flow.shed fault did not fire")
    except FaultInjected:
        pass
    assert fc.shed_mode("t") == "ok"
