"""Compact agent protocol + transport breadth tests:

- SWB1 MSG_REGISTRATION / MSG_REGISTRATION_ACK codec round trips
- THE e2e check [SURVEY.md §2.1 agent proto]: an unknown device
  registers OVER THE WIRE (MQTT) and receives its binary ack on its own
  command topic, then streams telemetry that scores
- WebSocket receiver: handshake + masked frames + fragmentation +
  ping/pong carrying SWB1 into the pipeline; command downlink over the
  same socket
- MQTT broker semantics: live pub/sub fan-out + retained messages
"""

import asyncio
import base64
import hashlib
import os
import struct

import numpy as np

from sitewhere_tpu.domain.batch import (
    ACK_ALREADY,
    ACK_NEW,
    BatchContext,
    RegistrationAck,
    RegistrationBatch,
)
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

from tests.test_mqtt import (
    _pkt,
    connect_pkt,
    publish_pkt,
    read_pkt,
    subscribe_pkt,
)
from tests.test_pipeline import running_pipeline, wait_until


def test_registration_codec_roundtrip():
    ctx = BatchContext(tenant_id="t")
    reg = RegistrationBatch(ctx, ["dev-a", "dev-b"], "pump",
                            area_token="plant-1")
    out = RegistrationBatch.decode(reg.encode(), ctx)
    assert out.device_tokens == ["dev-a", "dev-b"]
    assert out.device_type_token == "pump"
    assert out.area_token == "plant-1"

    ack = RegistrationAck(["dev-a", "dev-b"], [ACK_NEW, ACK_ALREADY],
                          [17, -1])
    out = RegistrationAck.decode(ack.encode())
    assert out.device_tokens == ["dev-a", "dev-b"]
    assert out.status == [ACK_NEW, ACK_ALREADY]
    assert out.device_index == [17, -1]


def test_unknown_device_registers_over_mqtt_and_gets_ack(run):
    """E2e: CONNECT as the device token → SUBSCRIBE own command topic →
    PUBLISH a binary registration → binary ack arrives on the command
    topic with the assigned dense index → telemetry for that index flows
    through the pipeline."""

    async def main():
        from sitewhere_tpu.services import (
            CommandDeliveryService,
            DeviceRegistrationService,
        )

        sections = {
            "event-sources": {"receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "mqtt", "decoder": "swb1", "name": "mqtt"}]},
            "rule-processing": {"model": None},
            "command-delivery": {"provider": "mqtt", "encoder": "json"},
            "device-registration": {"allow_unknown_devices": True,
                                    "default_device_type": "thermo"},
        }
        async with running_pipeline(
                num_devices=20, sections=sections,
                extra_services=(CommandDeliveryService,
                                DeviceRegistrationService)) as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", receiver.port)
            writer.write(connect_pkt("sensor-new-1"))
            await writer.drain()
            ptype, _, body = await read_pkt(reader)
            assert ptype == 2 and body[1] == 0
            writer.write(subscribe_pkt("swx/commands/sensor-new-1"))
            await writer.drain()
            ptype, _, _ = await read_pkt(reader)
            assert ptype == 9

            # the compact binary registration request
            reg = RegistrationBatch(BatchContext(tenant_id="acme"),
                                    ["sensor-new-1"], "thermo")
            writer.write(publish_pkt("swx/register", reg.encode(), qos=1,
                                     packet_id=5))
            await writer.drain()
            ptype, _, _ = await read_pkt(reader)
            assert ptype == 4  # PUBACK

            # the binary ack arrives on OUR command topic
            ptype, _, body = await read_pkt(reader)
            assert ptype == 3  # PUBLISH
            tlen = int.from_bytes(body[:2], "big")
            topic = body[2:2 + tlen].decode()
            assert topic == "swx/commands/sensor-new-1"
            ack = RegistrationAck.decode(body[2 + tlen:])
            assert ack.device_tokens == ["sensor-new-1"]
            assert ack.status == [ACK_NEW]
            new_index = ack.device_index[0]
            assert new_index == 20  # next dense slot after the fleet

            dm = rt.api("device-management").management("acme")
            assert dm.get_device_by_token("sensor-new-1") is not None

            # redelivery is idempotent: ACK_ALREADY with the same index
            writer.write(publish_pkt("swx/register", reg.encode(), qos=1,
                                     packet_id=6))
            await writer.drain()
            ptype, _, _ = await read_pkt(reader)  # PUBACK
            ptype, _, body = await read_pkt(reader)
            tlen = int.from_bytes(body[:2], "big")
            ack2 = RegistrationAck.decode(body[2 + tlen:])
            assert ack2.status == [ACK_ALREADY]
            assert ack2.device_index == [new_index]

            # the registered device's telemetry flows end to end
            from sitewhere_tpu.domain.batch import MeasurementBatch

            batch = MeasurementBatch(
                BatchContext(tenant_id="acme"),
                np.asarray([new_index], np.uint32),
                np.zeros(1, np.uint16), np.asarray([21.5], np.float32),
                np.asarray([1000.0]))
            writer.write(publish_pkt("swx/telemetry", batch.encode()))
            await writer.drain()
            em = rt.api("event-management").management("acme")
            await wait_until(
                lambda: em.telemetry.total_events >= 1)
            writer.close()

    run(main())


# -- WebSocket ---------------------------------------------------------------


def _ws_client_frame(payload: bytes, opcode: int = 0x2,
                     fin: bool = True) -> bytes:
    mask = os.urandom(4)
    head = bytearray([(0x80 if fin else 0) | opcode])
    n = len(payload)
    if n < 126:
        head.append(0x80 | n)
    elif n < 65536:
        head.append(0x80 | 126)
        head += n.to_bytes(2, "big")
    else:
        head.append(0x80 | 127)
        head += n.to_bytes(8, "big")
    masked = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
    return bytes(head) + mask + masked


async def _ws_connect(port: int, path: str):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    key = base64.b64encode(os.urandom(16)).decode()
    writer.write((f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                  f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                  f"Sec-WebSocket-Key: {key}\r\n"
                  f"Sec-WebSocket-Version: 13\r\n\r\n").encode())
    await writer.drain()
    resp = await reader.readuntil(b"\r\n\r\n")
    assert b"101" in resp.split(b"\r\n")[0]
    expect = base64.b64encode(hashlib.sha1(
        (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode())
        .digest())
    assert expect in resp
    return reader, writer


async def _ws_read_frame(reader):
    b1, b2 = await reader.readexactly(2)
    length = b2 & 0x7F
    if length == 126:
        length = int.from_bytes(await reader.readexactly(2), "big")
    payload = await reader.readexactly(length) if length else b""
    return b1 & 0x0F, payload


def test_websocket_ingest_fragmentation_and_downlink(run):
    async def main():
        from sitewhere_tpu.services import CommandDeliveryService

        sections = {
            "event-sources": {"receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "websocket", "decoder": "swb1", "name": "websocket"}]},
            "rule-processing": {"model": None},
            "command-delivery": {"provider": "websocket",
                                 "encoder": "json"},
        }
        async with running_pipeline(
                num_devices=10, sections=sections,
                extra_services=(CommandDeliveryService,)) as rt:
            receiver = rt.api("event-sources").engine("acme") \
                .receiver("websocket")
            reader, writer = await _ws_connect(receiver.port, "/ws/dev-3")

            sim = DeviceSimulator(SimConfig(num_devices=10), tenant_id="acme")
            payload, _ = sim.payload(t=0.0)
            writer.write(_ws_client_frame(payload))
            await writer.drain()
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 10)

            # fragmented message: two frames, one SWB1 payload
            payload2, _ = sim.payload(t=60.0)
            half = len(payload2) // 2
            writer.write(_ws_client_frame(payload2[:half], opcode=0x2,
                                          fin=False))
            writer.write(_ws_client_frame(payload2[half:], opcode=0x0))
            await writer.drain()
            await wait_until(lambda: em.telemetry.total_events == 20)

            # ping → pong
            writer.write(_ws_client_frame(b"hb", opcode=0x9))
            await writer.drain()
            opcode, pong = await _ws_read_frame(reader)
            assert opcode == 0xA and pong == b"hb"

            # command downlink rides the same socket
            from sitewhere_tpu.domain.events import DeviceCommandInvocation
            from sitewhere_tpu.domain.model import DeviceCommand

            dm = rt.api("device-management").management("acme")
            device = dm.get_device_by_token("dev-3")
            dt = dm.get_device_type_by_token("thermo")
            cmd = dm.create_device_command(DeviceCommand(
                token="reboot", device_type_id=dt.id, name="reboot"))
            assignment = dm.get_active_assignments_for_device(device.id)[0]
            await em.add_command_invocations([DeviceCommandInvocation(
                device_id=device.id, assignment_id=assignment.id,
                command_id=cmd.id)])
            opcode, frame = await asyncio.wait_for(_ws_read_frame(reader),
                                                   10.0)
            assert opcode == 0x2 and b"reboot" in frame

            # close handshake
            writer.write(_ws_client_frame(struct.pack("!H", 1000),
                                          opcode=0x8))
            await writer.drain()
            opcode, _ = await _ws_read_frame(reader)
            assert opcode == 0x8
            writer.close()

    run(main())


# -- MQTT broker fan-out ------------------------------------------------------


def test_mqtt_fan_out_and_retained(run):
    async def main():
        sections = {"event-sources": {"receivers": [
            {"kind": "mqtt", "decoder": "swb1", "name": "mqtt",
             # fan-out subscriptions are default-deny; the operator opens
             # the ops namespace explicitly
             "subscribe_allow": ["plant/"]}]},
            "rule-processing": {"model": None}}
        async with running_pipeline(num_devices=5, sections=sections) as rt:
            receiver = rt.api("event-sources").engine("acme").receiver("mqtt")

            # publisher retains a status message
            r1, w1 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w1.write(connect_pkt("publisher"))
            await w1.drain()
            await read_pkt(r1)
            w1.write(_pkt(3, 0x1, (len("plant/status")).to_bytes(2, "big")
                          + b"plant/status" + b"all-good"))  # retain flag
            await w1.drain()

            # later subscriber gets the retained message with retain set
            r2, w2 = await asyncio.open_connection("127.0.0.1", receiver.port)
            w2.write(connect_pkt("observer"))
            await w2.drain()
            await read_pkt(r2)
            w2.write(subscribe_pkt("plant/+"))
            await w2.drain()
            ptype, _, _ = await read_pkt(r2)
            assert ptype == 9  # SUBACK first
            ptype, flags, body = await read_pkt(r2)
            assert ptype == 3 and flags & 0x1  # retained PUBLISH
            tlen = int.from_bytes(body[:2], "big")
            assert body[2:2 + tlen] == b"plant/status"
            assert body[2 + tlen:] == b"all-good"

            # live fan-out: a fresh publish reaches the subscriber,
            # not the publisher itself
            w1.write(_pkt(3, 0, (len("plant/floor2")).to_bytes(2, "big")
                          + b"plant/floor2" + b"hot"))
            await w1.drain()
            ptype, flags, body = await read_pkt(r2)
            assert ptype == 3 and not flags & 0x1
            tlen = int.from_bytes(body[:2], "big")
            assert body[2 + tlen:] == b"hot"
            w1.close()
            w2.close()

    run(main())


# -- WebSocket security (advisor round-3 findings) ---------------------------


async def _ws_try_connect(port: int, path: str, headers: str = ""):
    """Raw Upgrade attempt; returns the HTTP status code line."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    key = base64.b64encode(os.urandom(16)).decode()
    writer.write((f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                  f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                  f"Sec-WebSocket-Key: {key}\r\n"
                  f"Sec-WebSocket-Version: 13\r\n"
                  f"{headers}\r\n").encode())
    await writer.drain()
    resp = await reader.readuntil(b"\r\n\r\n")
    status = resp.split(b"\r\n")[0].decode()
    return status, reader, writer


def test_websocket_auth_and_duplicate_rejection(run):
    """An unauthenticated peer must not occupy a session slot (the
    registry routes command downlink by client id, and ids are printed
    in QR labels). Duplicate ids REPLACE the existing session (MQTT
    CONNECT takeover semantics): a device rebooting after an unclean
    disconnect must be able to reconnect — there is no server-side ping
    to reap dead sockets — and with auth on the newcomer proved
    ownership, so hijack requires the token."""

    async def main():
        from sitewhere_tpu.services.websocket import WebSocketListener

        got = []

        async def on_message(payload, client_id):
            got.append((client_id, payload))

        listener = WebSocketListener(
            on_message,
            authenticate=lambda cid, tok: tok == f"secret-{cid}")
        await listener.start()
        try:
            # no token → 401, no session
            status, _, w = await _ws_try_connect(listener.port, "/ws/dev-1")
            assert "401" in status
            w.close()
            assert "dev-1" not in listener.sessions
            # wrong token → 401
            status, _, w = await _ws_try_connect(
                listener.port, "/ws/dev-1",
                "Authorization: Bearer nope\r\n")
            assert "401" in status
            w.close()
            # right token (header) → 101 + session registered
            status, r1, w1 = await _ws_try_connect(
                listener.port, "/ws/dev-1",
                "Authorization: Bearer secret-dev-1\r\n")
            assert "101" in status
            assert "dev-1" in listener.sessions
            first_session = listener.sessions["dev-1"]
            # the authenticated session ingests
            w1.write(_ws_client_frame(b"hello"))
            await w1.drain()
            await wait_until(lambda: len(got) == 1, timeout=5.0)
            assert got[0] == ("dev-1", b"hello")
            # PROVEN duplicate (device rebooted, same token) replaces the
            # stale session — not locked out until process restart
            status, r2, w2 = await _ws_try_connect(
                listener.port, "/ws/dev-1",
                "Authorization: Bearer secret-dev-1\r\n")
            assert "101" in status
            second = listener.sessions["dev-1"]
            assert second is not first_session
            # the new session ingests; the stale handler's teardown must
            # NOT evict it (identity-guarded cleanup)
            await asyncio.sleep(0.1)  # let the old handler unwind
            assert listener.sessions.get("dev-1") is second
            w2.write(_ws_client_frame(b"again"))
            await w2.drain()
            await wait_until(lambda: len(got) == 2, timeout=5.0)
            assert got[1] == ("dev-1", b"again")
            # query-param token form also accepted
            status, _, w3 = await _ws_try_connect(
                listener.port, "/ws/dev-2?token=secret-dev-2")
            assert "101" in status
            # session closing frees the id for reconnection
            w2.close()
            await wait_until(lambda: "dev-1" not in listener.sessions,
                             timeout=5.0)
            w1.close()
            w3.close()
        finally:
            await listener.stop()

        # open mode (loopback/test): takeover applies too — a 409 would
        # hand any peer a lockout primitive without adding protection
        open_listener = WebSocketListener(on_message)
        await open_listener.start()
        try:
            status, _, w1 = await _ws_try_connect(open_listener.port,
                                                  "/ws/dev-9")
            assert "101" in status
            first = open_listener.sessions["dev-9"]
            status, _, w2 = await _ws_try_connect(open_listener.port,
                                                  "/ws/dev-9")
            assert "101" in status
            assert open_listener.sessions["dev-9"] is not first
            w1.close()
            w2.close()
        finally:
            await open_listener.stop()

    run(main())


# -- CoAP (RFC 7252) ---------------------------------------------------------


def _coap_post(path: str, payload: bytes, mid: int, mtype: int = 0,
               token: bytes = b"\x42") -> bytes:
    """Minimal client-side CoAP POST with Uri-Path options."""
    out = bytearray([(1 << 6) | (mtype << 4) | len(token), 0x02])
    out += mid.to_bytes(2, "big")
    out += token
    number = 0
    for seg in path.split("/"):
        seg_b = seg.encode()
        delta = 11 - number
        assert delta < 13 and len(seg_b) < 13  # test-sized paths
        out.append((delta << 4) | len(seg_b))
        out += seg_b
        number = 11
    if payload:
        out += b"\xff" + payload
    return bytes(out)


class _UdpClient(asyncio.DatagramProtocol):
    def __init__(self):
        self.replies: asyncio.Queue = asyncio.Queue()
        self.transport = None

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        self.replies.put_nowait(data)


async def _udp_client(port: int) -> _UdpClient:
    loop = asyncio.get_running_loop()
    _, proto = await loop.create_datagram_endpoint(
        _UdpClient, remote_addr=("127.0.0.1", port))
    return proto


def test_coap_ingest_scores_anomaly_and_dedups_retransmit(run):
    """e2e: an SWB1 payload POSTed over CoAP (CON) is ACKed (2.04,
    token+mid echoed), decoded, persisted, and scored into an anomaly
    alert; a retransmitted CON re-ACKs without double-ingesting."""

    async def main():
        sections = {
            "event-sources": {"receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "coap", "decoder": "swb1", "name": "coap"}]},
            "rule-processing": {"model": "zscore",
                                "model_config": {"window": 16},
                                "threshold": 5.0, "batch_window_ms": 1.0},
        }
        async with running_pipeline(num_devices=20,
                                    sections=sections) as rt:
            sim = DeviceSimulator(SimConfig(num_devices=20, seed=9),
                                  tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme") \
                .receiver("default")
            for k in range(20):
                await receiver.submit(sim.payload(t=60.0 * k)[0])
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 400)

            coap = rt.api("event-sources").engine("acme").receiver("coap")
            client = await _udp_client(coap.port)
            sim.cfg = SimConfig(num_devices=20, seed=9, anomaly_rate=1.0,
                                anomaly_magnitude=20.0)
            payload, truth = sim.payload(t=21 * 60.0)
            assert truth.all()
            msg = _coap_post("telemetry", payload, mid=7, mtype=0)
            client.transport.sendto(msg)
            ack = await asyncio.wait_for(client.replies.get(), 5.0)
            # ACK (type 2), code 2.04, mid 7, token echoed
            assert (ack[0] >> 4) & 0x3 == 2
            assert ack[1] == 0x44
            assert int.from_bytes(ack[2:4], "big") == 7
            assert ack[4:5] == b"\x42"

            await wait_until(
                lambda: em.telemetry.total_events == 420, timeout=10.0)
            await wait_until(
                lambda: any(a.event_date == 21 * 60.0
                            for a in em.list_alerts()), timeout=15.0)

            # retransmission (same mid): re-ACKed, NOT re-ingested
            client.transport.sendto(msg)
            ack2 = await asyncio.wait_for(client.replies.get(), 5.0)
            assert ack2[1] == 0x44
            await asyncio.sleep(0.3)
            assert em.telemetry.total_events == 420
            assert coap.listener.accepted == 1

            # NON (type 1) with a fresh payload ingests silently
            payload2, _ = sim.payload(t=22 * 60.0)
            client.transport.sendto(
                _coap_post("telemetry", payload2, mid=8, mtype=1))
            await wait_until(
                lambda: em.telemetry.total_events == 440, timeout=10.0)
            client.transport.close()

    run(main())


def test_coap_malformed_fuzz_and_error_codes(run):
    """Fuzzed datagrams must never kill the endpoint; bad paths/methods
    get the right 4.xx piggybacked codes."""

    async def main():
        from sitewhere_tpu.services.coap import CoapListener

        got = []

        async def on_payload(payload, source):
            got.append(payload)

        listener = CoapListener(on_payload, path="telemetry")
        await listener.start()
        try:
            client = await _udp_client(listener.port)
            rng = np.random.default_rng(0)
            valid = _coap_post("telemetry", b"x" * 20, mid=1)
            for i in range(200):
                n = int(rng.integers(0, 64))
                client.transport.sendto(bytes(rng.integers(0, 256, n,
                                                           dtype=np.uint8)))
                # truncations of a valid message too
                client.transport.sendto(valid[:int(rng.integers(0,
                                                                len(valid)))])
            await asyncio.sleep(0.2)
            assert listener.malformed > 0
            # endpoint still alive and correct after the fuzz:
            # wrong path → 4.04
            client.transport.sendto(_coap_post("nope", b"x", mid=2))
            replies = client.replies
            while True:  # drain any RSTs the fuzz provoked
                r = await asyncio.wait_for(replies.get(), 5.0)
                if int.from_bytes(r[2:4], "big") == 2:
                    break
            assert r[1] == 0x84
            # retransmission of the REJECTED request replays 4.04 — a
            # lost error ACK must not turn into 2.04 success on retry
            client.transport.sendto(_coap_post("nope", b"x", mid=2))
            while True:
                r = await asyncio.wait_for(replies.get(), 5.0)
                if int.from_bytes(r[2:4], "big") == 2:
                    break
            assert r[1] == 0x84
            # GET → 4.05
            get = bytearray(_coap_post("telemetry", b"", mid=3))
            get[1] = 0x01
            client.transport.sendto(bytes(get))
            while True:
                r = await asyncio.wait_for(replies.get(), 5.0)
                if int.from_bytes(r[2:4], "big") == 3:
                    break
            assert r[1] == 0x85
            # and a valid POST still lands
            client.transport.sendto(_coap_post("telemetry", b"hello", mid=4))
            while True:
                r = await asyncio.wait_for(replies.get(), 5.0)
                if int.from_bytes(r[2:4], "big") == 4:
                    break
            assert r[1] == 0x44
            # (a truncation that cuts inside the payload is itself a
            # well-formed shorter message — UDP length delimits the
            # payload — so the fuzz may have legitimately ingested one)
            await wait_until(lambda: b"hello" in got, timeout=5.0)
            client.transport.close()
        finally:
            await listener.stop()

    run(main())


# -- AMQP 0-9-1 --------------------------------------------------------------


def _amqp_frame(ftype: int, channel: int, payload: bytes) -> bytes:
    return struct.pack(">BHI", ftype, channel, len(payload)) + payload + b"\xce"


def _amqp_method(class_id: int, method_id: int, args: bytes = b"") -> bytes:
    return struct.pack(">HH", class_id, method_id) + args


def _amqp_ss(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def _amqp_ls(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


async def _amqp_read_frame(reader) -> tuple[int, int, bytes]:
    head = await asyncio.wait_for(reader.readexactly(7), 5.0)
    ftype, channel, size = struct.unpack(">BHI", head)
    payload = await asyncio.wait_for(reader.readexactly(size + 1), 5.0)
    assert payload[-1] == 0xCE
    return ftype, channel, payload[:-1]


async def _amqp_expect(reader, class_id: int, method_id: int) -> bytes:
    """Read method frames (skipping heartbeats) until the expected one."""
    while True:
        ftype, _, payload = await _amqp_read_frame(reader)
        if ftype == 8:
            continue
        got = struct.unpack_from(">HH", payload, 0)
        assert got == (class_id, method_id), f"got {got}"
        return payload[4:]


async def _amqp_connect(port: int, user: str = "gw",
                        password: str = "pw"):
    """Client-side 0-9-1 connection + channel-1 open."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(b"AMQP\x00\x00\x09\x01")
    await _amqp_expect(reader, 10, 10)                      # start
    plain = b"\x00" + user.encode() + b"\x00" + password.encode()
    writer.write(_amqp_frame(1, 0, _amqp_method(
        10, 11, struct.pack(">I", 0) + _amqp_ss("PLAIN")
        + _amqp_ls(plain) + _amqp_ss("en_US"))))
    ftype, _, payload = await _amqp_read_frame(reader)
    class_id, method_id = struct.unpack_from(">HH", payload, 0)
    if (class_id, method_id) == (10, 50):                   # close (403)
        code = struct.unpack_from(">H", payload, 4)[0]
        writer.close()
        raise PermissionError(f"refused: {code}")
    assert (class_id, method_id) == (10, 30)                # tune
    writer.write(_amqp_frame(1, 0, _amqp_method(
        10, 31, struct.pack(">HIH", 0, 131072, 0))))        # tune-ok
    writer.write(_amqp_frame(1, 0, _amqp_method(
        10, 40, _amqp_ss("/") + _amqp_ss("") + b"\x00")))   # open
    await _amqp_expect(reader, 10, 41)                      # open-ok
    writer.write(_amqp_frame(1, 1, _amqp_method(20, 10, _amqp_ss(""))))
    await _amqp_expect(reader, 20, 11)                      # channel.open-ok
    return reader, writer


def _amqp_publish_frames(routing_key: str, body: bytes,
                         channel: int = 1) -> bytes:
    publish = _amqp_method(60, 40, struct.pack(">H", 0) + _amqp_ss("")
                           + _amqp_ss(routing_key) + b"\x00")
    header = struct.pack(">HHQH", 60, 0, len(body), 0)
    return (_amqp_frame(1, channel, publish)
            + _amqp_frame(2, channel, header)
            + _amqp_frame(3, channel, body))


def test_amqp_ingest_scores_anomaly_with_confirms(run):
    """e2e: SWB1 telemetry published over AMQP 0-9-1 (confirm mode) is
    basic.ack'd, decoded, persisted, and scored into an anomaly alert;
    queue.declare bookkeeping and multi-frame bodies work."""

    async def main():
        sections = {
            "event-sources": {"receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "amqp", "decoder": "swb1", "name": "amqp",
                 "users": {"gw": "pw"}}]},
            "rule-processing": {"model": "zscore",
                                "model_config": {"window": 16},
                                "threshold": 5.0, "batch_window_ms": 1.0},
        }
        async with running_pipeline(num_devices=20,
                                    sections=sections) as rt:
            sim = DeviceSimulator(SimConfig(num_devices=20, seed=9),
                                  tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme") \
                .receiver("default")
            for k in range(20):
                await receiver.submit(sim.payload(t=60.0 * k)[0])
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 400)

            amqp = rt.api("event-sources").engine("acme").receiver("amqp")
            reader, writer = await _amqp_connect(amqp.port)
            # declare-before-publish bookkeeping is acked
            writer.write(_amqp_frame(1, 1, _amqp_method(
                50, 10, struct.pack(">H", 0) + _amqp_ss("telemetry")
                + b"\x00" + struct.pack(">I", 0))))
            await _amqp_expect(reader, 50, 11)              # declare-ok
            writer.write(_amqp_frame(1, 1, _amqp_method(85, 10, b"\x00")))
            await _amqp_expect(reader, 85, 11)              # confirm select-ok

            sim.cfg = SimConfig(num_devices=20, seed=9, anomaly_rate=1.0,
                                anomaly_magnitude=20.0)
            payload, truth = sim.payload(t=21 * 60.0)
            assert truth.all()
            writer.write(_amqp_publish_frames("telemetry", payload))
            args = await _amqp_expect(reader, 60, 80)       # basic.ack
            assert struct.unpack_from(">Q", args, 0)[0] == 1

            await wait_until(
                lambda: em.telemetry.total_events == 420, timeout=10.0)
            await wait_until(
                lambda: any(a.event_date == 21 * 60.0
                            for a in em.list_alerts()), timeout=15.0)

            # multi-frame body: split a second payload across two body
            # frames under one content header
            payload2, _ = sim.payload(t=22 * 60.0)
            publish = _amqp_method(60, 40, struct.pack(">H", 0)
                                   + _amqp_ss("") + _amqp_ss("telemetry")
                                   + b"\x00")
            header = struct.pack(">HHQH", 60, 0, len(payload2), 0)
            mid = len(payload2) // 2
            writer.write(_amqp_frame(1, 1, publish)
                         + _amqp_frame(2, 1, header)
                         + _amqp_frame(3, 1, payload2[:mid])
                         + _amqp_frame(3, 1, payload2[mid:]))
            args = await _amqp_expect(reader, 60, 80)
            assert struct.unpack_from(">Q", args, 0)[0] == 2
            await wait_until(
                lambda: em.telemetry.total_events == 440, timeout=10.0)

            # clean close
            writer.write(_amqp_frame(1, 0, _amqp_method(
                10, 50, struct.pack(">H", 200) + _amqp_ss("bye")
                + struct.pack(">HH", 0, 0))))
            await _amqp_expect(reader, 10, 51)              # close-ok
            writer.close()

    run(main())


def test_amqp_auth_and_consume_refusal(run):
    """Wrong PLAIN credentials are refused with connection.close 403;
    basic.consume on an authenticated connection gets channel.close 540
    (ingest endpoint, not a broker); a bad protocol header is answered
    with the supported version."""

    async def main():
        from sitewhere_tpu.services.amqp import AmqpListener

        got = []

        async def on_message(key, body, source):
            got.append((key, body, source))

        listener = AmqpListener(
            on_message, authenticate=lambda u, p: (u, p) == ("gw", "pw"))
        await listener.start()
        try:
            # wrong password → PermissionError from the close frame
            try:
                await _amqp_connect(listener.port, "gw", "nope")
                raise AssertionError("expected refusal")
            except PermissionError as exc:
                assert "403" in str(exc)

            # right creds, then basic.consume → channel.close 540
            reader, writer = await _amqp_connect(listener.port)
            writer.write(_amqp_frame(1, 1, _amqp_method(
                60, 20, struct.pack(">H", 0) + _amqp_ss("q")
                + _amqp_ss("tag") + b"\x00" + struct.pack(">I", 0))))
            args = await _amqp_expect(reader, 20, 40)       # channel.close
            assert struct.unpack_from(">H", args, 0)[0] == 540
            # the connection survives; a reopened channel still publishes
            writer.write(_amqp_frame(1, 1, _amqp_method(20, 10,
                                                        _amqp_ss(""))))
            await _amqp_expect(reader, 20, 11)
            writer.write(_amqp_publish_frames("k", b"payload"))
            await wait_until(lambda: len(got) == 1, timeout=5.0)
            assert got[0] == ("k", b"payload", "gw")
            writer.close()

            # bad protocol header → server replies with its version
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", listener.port)
            writer.write(b"HTTP/1.1 GET /\r\n")
            reply = await asyncio.wait_for(reader.read(8), 5.0)
            assert reply == b"AMQP\x00\x00\x09\x01"
            writer.close()
        finally:
            await listener.stop()

    run(main())


def test_amqp_oversize_body_closes_channel_not_connection(run):
    """A publish whose declared body exceeds max_body gets channel.close
    311 while its in-flight body frames are swallowed — the connection
    (and a reopened channel) keeps working."""

    async def main():
        from sitewhere_tpu.services.amqp import AmqpListener

        got = []

        async def on_message(key, body, source):
            got.append(body)

        listener = AmqpListener(on_message, max_body=64)
        await listener.start()
        try:
            reader, writer = await _amqp_connect(listener.port)
            big = b"z" * 200
            writer.write(_amqp_publish_frames("k", big))
            args = await _amqp_expect(reader, 20, 40)       # channel.close
            assert struct.unpack_from(">H", args, 0)[0] == 311
            writer.write(_amqp_frame(1, 1, _amqp_method(20, 41)))  # close-ok
            # connection survives: reopen the channel, publish small
            writer.write(_amqp_frame(1, 1, _amqp_method(20, 10,
                                                        _amqp_ss(""))))
            await _amqp_expect(reader, 20, 11)
            writer.write(_amqp_publish_frames("k", b"small"))
            await wait_until(lambda: got == [b"small"], timeout=5.0)
            writer.close()
        finally:
            await listener.stop()

    run(main())


def test_coap_client_separate_response(run):
    """coap_post handles RFC 7252 §5.2.2 separate responses: an empty
    ACK stops retransmission, the later CON response (matched by token)
    is the result and gets ACKed back."""

    async def main():
        from sitewhere_tpu.services.coap import (
            CODE_CHANGED, CODE_EMPTY, TYPE_ACK, TYPE_CON,
            build_message, coap_post, parse_message)

        acks_seen = []

        class SlowServer(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                self.transport = transport

            def datagram_received(self, data, addr):
                mtype, code, mid, token, _, _ = parse_message(data)
                if mtype == TYPE_ACK:
                    acks_seen.append(mid)
                    return
                # empty ACK now, separate CON response shortly after
                self.transport.sendto(
                    build_message(TYPE_ACK, CODE_EMPTY, mid), addr)

                async def later():
                    await asyncio.sleep(0.15)
                    # response CON: echo token, fresh mid
                    out = bytearray(build_message(
                        TYPE_CON, CODE_CHANGED, 0x7777))
                    out[0] |= len(token)
                    out[4:4] = token
                    self.transport.sendto(bytes(out), addr)

                asyncio.get_running_loop().create_task(later())

        loop = asyncio.get_running_loop()
        transport, _ = await loop.create_datagram_endpoint(
            SlowServer, local_addr=("127.0.0.1", 0))
        port = transport.get_extra_info("sockname")[1]
        try:
            code = await coap_post("127.0.0.1", port, "commands", b"x",
                                   ack_timeout=0.5)
            assert code == CODE_CHANGED
            # our client ACKed the separate CON response
            await wait_until(lambda: 0x7777 in acks_seen, timeout=5.0)
        finally:
            transport.close()

    run(main())


def test_amqp_confirm_tags_nowait_and_aborted_oversize(run):
    """Delivery tags restart at 1 after confirm.select (publishes made
    before select don't count); a declare with the no-wait bit (0x10)
    gets no declare-ok; an oversize publish ABORTED before its body
    frames doesn't poison a reopened channel number."""

    async def main():
        from sitewhere_tpu.services.amqp import AmqpListener

        got = []

        async def on_message(key, body, source):
            got.append(body)

        listener = AmqpListener(on_message, max_body=64)
        await listener.start()
        try:
            reader, writer = await _amqp_connect(listener.port)
            # two publishes BEFORE confirm.select
            writer.write(_amqp_publish_frames("k", b"one"))
            writer.write(_amqp_publish_frames("k", b"two"))
            await wait_until(lambda: len(got) == 2, timeout=5.0)
            # no-wait declare: must NOT produce a declare-ok
            writer.write(_amqp_frame(1, 1, _amqp_method(
                50, 10, struct.pack(">H", 0) + _amqp_ss("q")
                + b"\x10" + struct.pack(">I", 0))))
            # select, then publish: ack tag must be 1, not 3
            writer.write(_amqp_frame(1, 1, _amqp_method(85, 10, b"\x00")))
            await _amqp_expect(reader, 85, 11)  # fails if declare-ok leaked
            writer.write(_amqp_publish_frames("k", b"three"))
            args = await _amqp_expect(reader, 60, 80)
            assert struct.unpack_from(">Q", args, 0)[0] == 1

            # oversize publish aborted BEFORE body frames: close-ok,
            # reopen same channel, a fresh publish must still deliver
            publish = _amqp_method(60, 40, struct.pack(">H", 0)
                                   + _amqp_ss("") + _amqp_ss("k") + b"\x00")
            header = struct.pack(">HHQH", 60, 0, 500, 0)  # > max_body
            writer.write(_amqp_frame(1, 1, publish)
                         + _amqp_frame(2, 1, header))
            args = await _amqp_expect(reader, 20, 40)
            assert struct.unpack_from(">H", args, 0)[0] == 311
            writer.write(_amqp_frame(1, 1, _amqp_method(20, 41)))
            writer.write(_amqp_frame(1, 1, _amqp_method(20, 10,
                                                        _amqp_ss(""))))
            await _amqp_expect(reader, 20, 11)
            writer.write(_amqp_publish_frames("k", b"fresh"))
            await wait_until(lambda: got[-1] == b"fresh", timeout=5.0)
            writer.close()
        finally:
            await listener.stop()

    run(main())


def test_amqp_malformed_fuzz_endpoint_survives(run):
    """Fuzzed bytes on the AMQP port (random garbage, truncated valid
    frame streams, giant declared frame sizes) kill at most their own
    connection — a clean client afterwards still publishes."""

    async def main():
        from sitewhere_tpu.services.amqp import AmqpListener

        got = []

        async def on_message(key, body, source):
            got.append(body)

        listener = AmqpListener(on_message)
        await listener.start()
        try:
            rng = np.random.default_rng(7)
            # a valid connection byte stream up to the publish, for
            # truncation fuzz
            plain = b"\x00gw\x00pw"
            valid = (b"AMQP\x00\x00\x09\x01"
                     + _amqp_frame(1, 0, _amqp_method(
                         10, 11, struct.pack(">I", 0) + _amqp_ss("PLAIN")
                         + _amqp_ls(plain) + _amqp_ss("en_US")))
                     + _amqp_frame(1, 0, _amqp_method(
                         10, 31, struct.pack(">HIH", 0, 131072, 0)))
                     + _amqp_frame(1, 0, _amqp_method(
                         10, 40, _amqp_ss("/") + _amqp_ss("") + b"\x00"))
                     + _amqp_frame(1, 1, _amqp_method(20, 10, _amqp_ss("")))
                     + _amqp_publish_frames("k", b"x"))
            for i in range(60):
                r, w = await asyncio.open_connection("127.0.0.1",
                                                     listener.port)
                kind = i % 3
                if kind == 0:      # pure garbage
                    n = int(rng.integers(1, 128))
                    w.write(bytes(rng.integers(0, 256, n, dtype=np.uint8)))
                elif kind == 1:    # truncated valid stream
                    cut = int(rng.integers(1, len(valid)))
                    w.write(valid[:cut])
                else:              # huge declared frame size after header
                    w.write(b"AMQP\x00\x00\x09\x01"
                            + struct.pack(">BHI", 1, 0, 0x7FFFFFFF))
                await w.drain()
                w.close()
            await asyncio.sleep(0.2)
            # endpoint alive: a clean client still connects + publishes
            before = len(got)
            reader, writer = await _amqp_connect(listener.port)
            writer.write(_amqp_publish_frames("k", b"after-fuzz"))
            await wait_until(lambda: len(got) > before, timeout=5.0)
            assert got[-1] == b"after-fuzz"
            writer.close()
        finally:
            await listener.stop()

    run(main())


# -- STOMP 1.2 ---------------------------------------------------------------


async def _stomp_read_frame(reader):
    data = await asyncio.wait_for(reader.readuntil(b"\x00"), 5.0)
    head, _, body = data[:-1].partition(b"\n\n")
    lines = head.decode().replace("\r\n", "\n").split("\n")
    headers = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        if k and k not in headers:
            headers[k] = v
    return lines[0], headers, body


def test_stomp_ingest_binary_receipts_and_auth(run):
    """e2e: SWB1 telemetry SENT over STOMP (content-length binary body,
    receipt handshake) is decoded, persisted, and scored; wrong
    credentials get an ERROR frame; a NUL-free text body also works."""

    async def main():
        sections = {
            "event-sources": {"receivers": [
                {"kind": "queue", "decoder": "swb1", "name": "default"},
                {"kind": "stomp", "decoder": "swb1", "name": "stomp",
                 "users": {"gw": "pw"}}]},
            "rule-processing": {"model": "zscore",
                                "model_config": {"window": 16},
                                "threshold": 5.0, "batch_window_ms": 1.0},
        }
        async with running_pipeline(num_devices=20,
                                    sections=sections) as rt:
            sim = DeviceSimulator(SimConfig(num_devices=20, seed=9),
                                  tenant_id="acme")
            receiver = rt.api("event-sources").engine("acme") \
                .receiver("default")
            for k in range(20):
                await receiver.submit(sim.payload(t=60.0 * k)[0])
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events == 400)

            stomp = rt.api("event-sources").engine("acme").receiver("stomp")
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", stomp.port)
            writer.write(b"CONNECT\naccept-version:1.2\nlogin:gw\n"
                         b"passcode:pw\n\n\x00")
            cmd, headers, _ = await _stomp_read_frame(reader)
            assert cmd == "CONNECTED" and headers["version"] == "1.2"

            sim.cfg = SimConfig(num_devices=20, seed=9, anomaly_rate=1.0,
                                anomaly_magnitude=20.0)
            payload, truth = sim.payload(t=21 * 60.0)
            assert truth.all()
            writer.write(b"SEND\ndestination:/queue/telemetry\n"
                         + f"content-length:{len(payload)}\n".encode()
                         + b"receipt:r1\n\n" + payload + b"\x00")
            cmd, headers, _ = await _stomp_read_frame(reader)
            assert cmd == "RECEIPT" and headers["receipt-id"] == "r1"
            await wait_until(
                lambda: em.telemetry.total_events == 420, timeout=10.0)
            await wait_until(
                lambda: any(a.event_date == 21 * 60.0
                            for a in em.list_alerts()), timeout=15.0)

            # clean disconnect with receipt
            writer.write(b"DISCONNECT\nreceipt:r2\n\n\x00")
            cmd, headers, _ = await _stomp_read_frame(reader)
            assert cmd == "RECEIPT" and headers["receipt-id"] == "r2"
            writer.close()

            # wrong passcode → ERROR frame
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", stomp.port)
            writer.write(b"CONNECT\nlogin:gw\npasscode:nope\n\n\x00")
            cmd, headers, _ = await _stomp_read_frame(reader)
            assert cmd == "ERROR"
            writer.close()

            # CRLF-framed client (spec allows EOL = \r\n) must work,
            # and a receipt id with an escaped newline must round-trip
            # escaped in the RECEIPT (no header-line injection)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", stomp.port)
            writer.write(b"CONNECT\r\naccept-version:1.2\r\n"
                         b"login:gw\r\npasscode:pw\r\n\r\n\x00")
            cmd, headers, _ = await _stomp_read_frame(reader)
            assert cmd == "CONNECTED"
            writer.write(b"SEND\r\ndestination:d\r\n"
                         b"receipt:a\\nb\r\n\r\ncrlf-body\x00")
            raw = await asyncio.wait_for(reader.readuntil(b"\x00"), 5.0)
            assert b"receipt-id:a\\nb\n" in raw   # escaped, not injected
            await wait_until(
                lambda: em.telemetry.total_events == 420, timeout=10.0)
            writer.close()

    run(main())


def test_stomp_fuzz_and_unsupported_frames(run):
    """Garbage and truncated streams kill at most their own connection;
    SUBSCRIBE gets a receipt (strict clients don't stall); unsupported
    frames get an ERROR frame."""

    async def main():
        from sitewhere_tpu.services.stomp import StompListener

        got = []

        async def on_message(dest, body, source):
            got.append((dest, body))

        listener = StompListener(on_message)
        await listener.start()
        try:
            rng = np.random.default_rng(11)
            valid = (b"CONNECT\n\n\x00"
                     b"SEND\ndestination:d\n\nhello\x00")
            for i in range(40):
                r, w = await asyncio.open_connection("127.0.0.1",
                                                     listener.port)
                if i % 2:
                    n = int(rng.integers(1, 96))
                    w.write(bytes(rng.integers(0, 256, n, dtype=np.uint8)))
                else:
                    w.write(valid[:int(rng.integers(1, len(valid)))])
                await w.drain()
                w.close()
            # endpoint alive; subscribe ack'd; bad frame → ERROR
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", listener.port)
            writer.write(b"STOMP\naccept-version:1.2\n\n\x00")
            cmd, _, _ = await _stomp_read_frame(reader)
            assert cmd == "CONNECTED"
            writer.write(b"SUBSCRIBE\nid:0\ndestination:d\nreceipt:s\n\n\x00")
            cmd, headers, _ = await _stomp_read_frame(reader)
            assert cmd == "RECEIPT" and headers["receipt-id"] == "s"
            writer.write(b"SEND\ndestination:d\n\npayload-text\x00")
            await wait_until(lambda: got == [("d", b"payload-text")],
                             timeout=5.0)
            writer.write(b"WAT\n\n\x00")
            cmd, _, _ = await _stomp_read_frame(reader)
            assert cmd == "ERROR"
            writer.close()
        finally:
            await listener.stop()

    run(main())


def test_coap_shared_secret_auth(run):
    """With a listener secret set, POSTs carrying the Uri-Query
    `token=<secret>` are ingested; wrong/missing tokens get 4.01 and are
    never decoded (counted in `unauthorized`). CoAP here is cleartext
    UDP — the secret gates misdirected traffic, not an on-path attacker
    (documented deployment caveat, services/coap.py)."""

    async def main():
        from sitewhere_tpu.services.coap import (
            CODE_CHANGED,
            CODE_UNAUTHORIZED,
            CoapListener,
            coap_post,
        )

        got = []

        async def on_payload(payload, source):
            got.append(payload)

        listener = CoapListener(on_payload, path="telemetry",
                                secret="s3cr3t")
        await listener.start()
        try:
            # right token → 2.04, payload ingested
            code = await coap_post("127.0.0.1", listener.port,
                                   "telemetry", b"authed-payload",
                                   secret="s3cr3t")
            assert code == CODE_CHANGED
            await wait_until(lambda: got == [b"authed-payload"])
            # wrong token → 4.01, nothing ingested
            code = await coap_post("127.0.0.1", listener.port,
                                   "telemetry", b"intruder",
                                   secret="wrong")
            assert code == CODE_UNAUTHORIZED
            # missing token → 4.01
            code = await coap_post("127.0.0.1", listener.port,
                                   "telemetry", b"anonymous")
            assert code == CODE_UNAUTHORIZED
            await asyncio.sleep(0.1)
            assert got == [b"authed-payload"]
            assert listener.unauthorized == 2
            # NON without a token is silently dropped (nothing to ACK)
            from sitewhere_tpu.sim.clients import CoapSender

            s = CoapSender("127.0.0.1", listener.port)
            await s.connect()
            await s.send(b"non-anon")
            await s.close()
            s2 = CoapSender("127.0.0.1", listener.port, secret="s3cr3t")
            await s2.connect()
            await s2.send(b"non-authed")
            await s2.close()
            await wait_until(
                lambda: got == [b"authed-payload", b"non-authed"])
            assert listener.unauthorized == 3
        finally:
            await listener.stop()

    run(main())
