"""Ring attention (parallel/ring.py): exact parity with dense attention
on the 8-virtual-device CPU mesh [SURVEY.md §5.7, §2.4 SP/CP row]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from sitewhere_tpu.parallel.ring import (
    dense_attention_reference,
    ring_attention_sharded,
)


def _mesh(n=8, name="seq"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _qkv(rng, B, W, H, Dh):
    ks = jax.random.split(rng, 3)
    shape = (B, W, H, Dh)
    return (jax.random.normal(ks[0], shape, jnp.float32),
            jax.random.normal(ks[1], shape, jnp.float32),
            jax.random.normal(ks[2], shape, jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    B, W, H, Dh = 2, 64, 2, 8
    q, k, v = _qkv(jax.random.PRNGKey(0), B, W, H, Dh)
    valid = jnp.ones((B, W), bool)
    mesh = _mesh()
    out = ring_attention_sharded(q, k, v, valid, mesh, "seq", causal=causal)
    ref = dense_attention_reference(q, k, v, valid, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_respects_validity_mask():
    """Padded (invalid) timesteps must not contribute as keys."""
    B, W, H, Dh = 1, 32, 1, 4
    q, k, v = _qkv(jax.random.PRNGKey(1), B, W, H, Dh)
    valid = jnp.arange(W)[None, :] >= 10   # first 10 slots are padding
    mesh = _mesh()
    out = ring_attention_sharded(q, k, v, valid, mesh, "seq")
    ref = dense_attention_reference(q, k, v, valid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # and the padded-key region genuinely changed nothing: perturbing
    # masked k/v leaves the output identical
    k2 = k.at[:, :10].set(999.0)
    v2 = v.at[:, :10].set(-999.0)
    out2 = ring_attention_sharded(q, k2, v2, valid, mesh, "seq")
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                               rtol=1e-6, atol=1e-6)


def test_ring_fully_masked_rows_are_zero():
    B, W, H, Dh = 1, 16, 1, 4
    q, k, v = _qkv(jax.random.PRNGKey(2), B, W, H, Dh)
    valid = jnp.zeros((B, W), bool)
    mesh = _mesh()
    out = ring_attention_sharded(q, k, v, valid, mesh, "seq")
    assert np.abs(np.asarray(out)).max() == 0.0


def test_ring_bfloat16_inputs():
    """bf16 q/k/v (the MXU path) accumulate in f32 and stay close to the
    f32 dense reference."""
    B, W, H, Dh = 2, 64, 2, 8
    q, k, v = _qkv(jax.random.PRNGKey(3), B, W, H, Dh)
    valid = jnp.ones((B, W), bool)
    mesh = _mesh()
    out = ring_attention_sharded(q.astype(jnp.bfloat16),
                                 k.astype(jnp.bfloat16),
                                 v.astype(jnp.bfloat16), valid, mesh, "seq")
    ref = dense_attention_reference(q, k, v, valid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=0.06, atol=0.06)
