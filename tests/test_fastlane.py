"""Fused ingress fast lane (kernel/fastlane.py): lane selection, lane
equivalence against the staged slow lane, and the platform contracts
(DLQ quarantine, flow-control shed routing, chaos site) on the fused
path — ISSUE 5's acceptance tests.

Equivalence is behavioral: the SAME event sequence driven through a
fastlane-on and a fastlane-off runtime must produce identical scored
outputs, identical persisted telemetry, and identical
unregistered-device splits."""

import asyncio
import contextlib

import numpy as np

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.kernel.bus import TopicNaming
from sitewhere_tpu.kernel.fastlane import fastlane_enabled
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.services import (
    DeviceManagementService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
    RuleProcessingService,
)
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig
from tests.test_pipeline import wait_until

RULE = {"model": "zscore", "model_config": {"window": 16},
        "threshold": 6.0, "batch_window_ms": 1.0,
        "buckets": [256], "capacity": 256}


@contextlib.asynccontextmanager
async def lane_runtime(num_devices=32, fastlane=None, faults=None,
                       instance_id="lane"):
    """Full pipeline runtime with tenant 'acme'; `fastlane` pins the
    lane via the tenant override (None = auto-detection)."""
    rt = ServiceRuntime(InstanceSettings(instance_id=instance_id))
    for cls in (DeviceManagementService, EventSourcesService,
                InboundProcessingService, EventManagementService,
                DeviceStateService, RuleProcessingService):
        rt.add_service(cls(rt))
    if faults is not None:
        rt.install_faults(faults)
    await rt.start()
    sections = {"rule-processing": dict(RULE)}
    if fastlane is not None:
        sections["fastlane"] = {"enabled": fastlane}
    await rt.add_tenant(TenantConfig(tenant_id="acme", sections=sections))
    dm = rt.api("device-management").management("acme")
    dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), num_devices)
    session = rt.api("rule-processing").engine("acme").session
    await wait_until(lambda: session.ready, timeout=60.0)
    try:
        yield rt
    finally:
        await rt.stop()


def _measurements(n: int, t: float, start: int = 0) -> MeasurementBatch:
    return MeasurementBatch(
        BatchContext(tenant_id="acme", source="test"),
        np.arange(start, start + n, dtype=np.uint32),
        np.zeros(n, np.uint16), np.full(n, 21.0, np.float32),
        np.full(n, t))


# -- lane selection ---------------------------------------------------------

def test_lane_selection_and_wiring(run):
    async def main():
        # auto-detected ON: rule engine hosts the FastLane, inbound
        # engine does NOT spin its staged consumer
        async with lane_runtime() as rt:
            assert rt.api("rule-processing").engine("acme").fastlane \
                is not None
            assert rt.services["inbound-processing"] \
                .engines["acme"].processor is None
            # predicate declines config-declared custom rules (the
            # fully staged lane keeps their ordering story)
            scripted = TenantConfig(tenant_id="s", sections={
                "rule-processing": {"model": "zscore",
                                    "scripts": {"x": "pass"}}})
            assert not fastlane_enabled(scripted, rt)
            fenced = TenantConfig(tenant_id="f", sections={
                "rule-processing": {"model": "zscore",
                                    "geofences": [{"n": 1}]}})
            assert not fastlane_enabled(fenced, rt)
            # ... and scoring-disabled tenants (nothing to fuse toward)
            no_model = TenantConfig(tenant_id="n", sections={
                "rule-processing": {"model": None}})
            assert not fastlane_enabled(no_model, rt)
            # explicit override beats auto-detection either way
            forced_on = TenantConfig(tenant_id="o", sections={
                "fastlane": {"enabled": True},
                "rule-processing": {"model": "zscore",
                                    "scripts": {"x": "pass"}}})
            assert fastlane_enabled(forced_on, rt)
        # pinned OFF: staged lane wired exactly as before
        async with lane_runtime(fastlane=False, instance_id="lane2") as rt:
            assert rt.api("rule-processing").engine("acme").fastlane is None
            assert rt.services["inbound-processing"] \
                .engines["acme"].processor is not None

    run(main())


# -- lane equivalence -------------------------------------------------------

async def _drive_and_collect(rt, n_sim=48, ticks=6):
    """Feed `ticks` simulator payloads via the default receiver and
    return (scored {(device, ts) -> (score, is_anomaly)}, telemetry
    total, unregistered-record count)."""
    scored_topic = rt.naming.tenant_topic("acme", TopicNaming.SCORED_EVENTS)
    consumer = rt.bus.subscribe(scored_topic, group="lane-test-meter")
    sim = DeviceSimulator(SimConfig(num_devices=n_sim, seed=7),
                          tenant_id="acme")
    receiver = rt.api("event-sources").engine("acme").receiver("default")
    for k in range(ticks):
        payload, _ = sim.payload(t=1000.0 + 60.0 * k)
        assert await receiver.submit(payload)
    session = rt.api("rule-processing").engine("acme").session
    expected = 32 * ticks  # only the registered 32 of n_sim are scored
    await wait_until(lambda: session.latency.count >= expected,
                     timeout=30.0)
    em = rt.api("event-management").management("acme")
    await wait_until(lambda: em.telemetry.total_events >= expected,
                     timeout=30.0)
    # collect off the TOPIC, waiting on published records: with the
    # fused egress stage (kernel/egresslane.py) a settled flush is
    # published a beat later by the shard loop, so settle count alone
    # no longer implies the records are poll-able
    scored = {}

    def collect():
        for r in consumer.poll_nowait(max_records=512):
            b = r.value
            for i in range(len(b)):
                scored[(int(b.device_index[i]), float(b.ts[i]))] = (
                    round(float(b.score[i]), 3), bool(b.is_anomaly[i]))
        return len(scored) >= expected

    await wait_until(collect, timeout=30.0)
    consumer.close()
    unreg_topic = rt.naming.tenant_topic(
        "acme", TopicNaming.UNREGISTERED_DEVICES)
    unreg = sum(len(r.value["device_indices"])
                for r in rt.bus.peek(unreg_topic, limit=-1)
                if isinstance(r.value, dict))
    return scored, em.telemetry.total_events, unreg


def test_lane_equivalence_scored_outputs_and_splits(run):
    """Same event sequence, both lanes: identical per-event scores,
    identical persisted telemetry, identical unregistered splits."""
    async def main():
        async with lane_runtime(fastlane=True, instance_id="on") as rt_on:
            fast = await _drive_and_collect(rt_on)
            # the fused lane did the validation: its counters moved
            assert rt_on.metrics.meter(
                "fastlane.events_processed").rate(60.0) > 0
            assert rt_on.metrics.counter(
                "fastlane.events_unregistered").value == 16 * 6
        async with lane_runtime(fastlane=False, instance_id="off") as rt_off:
            slow = await _drive_and_collect(rt_off)
        scored_f, total_f, unreg_f = fast
        scored_s, total_s, unreg_s = slow
        assert total_f == total_s == 32 * 6
        assert unreg_f == unreg_s == 16 * 6
        assert scored_f.keys() == scored_s.keys()
        assert len(scored_f) == 32 * 6
        for key, (score, anom) in scored_f.items():
            assert scored_s[key] == (score, anom), key

    run(main())


def test_fastlane_batches_not_rescored_at_enriched_hop(run):
    """The ctx.fastlane flag stops the rule processor re-admitting what
    the fused loop already scored — exactly-once scoring per delivery."""
    async def main():
        async with lane_runtime() as rt:
            session = rt.api("rule-processing").engine("acme").session
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            await rt.bus.produce(decoded, _measurements(32, 1000.0),
                                 key="gw")
            await wait_until(lambda: session.latency.count >= 32)
            # the enriched hop has long since seen the batch; give any
            # (wrong) second admission time to surface
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: em.telemetry.total_events >= 32)
            await asyncio.sleep(0.3)
            assert session.latency.count == 32

    run(main())


def test_stale_fastlane_flag_cleared_by_staged_lane(run):
    """A record the fused lane handled mutates the shared ctx in the
    decoded-topic log; if it redelivers into the STAGED lane (lane
    toggle with uncommitted offsets), the stale flag must not make the
    rule processor skip scoring it — the staged lane reclaims the
    batch."""
    async def main():
        async with lane_runtime(fastlane=False, instance_id="stale") as rt:
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            batch = _measurements(32, 1000.0)
            batch.ctx.fastlane = True  # as a pre-toggle fused pass left it
            await rt.bus.produce(decoded, batch, key="gw")
            session = rt.api("rule-processing").engine("acme").session
            await wait_until(lambda: session.latency.count >= 32)

    run(main())


# -- contracts on the fused path --------------------------------------------

def test_fastlane_poison_record_quarantined(run):
    """DLQ01 behaviorally: a poison decoded record lands in the tenant
    DLQ with fastlane provenance and the lane keeps flowing."""
    async def main():
        from sitewhere_tpu.kernel.dlq import list_dead_letters

        async with lane_runtime() as rt:
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            dlq = rt.naming.tenant_topic("acme", TopicNaming.DEAD_LETTER)
            poison = _measurements(8, 1000.0)
            # string device indices break the registration-mask gather
            poison.device_index = np.array(["x"] * 8, dtype=object)
            await rt.bus.produce(decoded, poison, key="gw")
            await rt.bus.produce(decoded, _measurements(32, 1001.0),
                                 key="gw")
            session = rt.api("rule-processing").engine("acme").session
            await wait_until(lambda: session.latency.count >= 32)
            entries = list_dead_letters(rt.bus, dlq)
            assert len(entries) == 1
            assert "fastlane" in entries[0][1]["stage"]
            assert entries[0][1]["original_topic"] == decoded

    run(main())


def test_fastlane_chaos_site_armed(run):
    """`fastlane.handle` is a registered chaos site: injected faults
    quarantine exactly the faulted records, the loop survives."""
    async def main():
        from sitewhere_tpu.kernel.dlq import list_dead_letters
        from sitewhere_tpu.kernel.faults import FaultInjector
        from sitewhere_tpu.kernel.lifecycle import LifecycleStatus

        fi = FaultInjector(seed=11)
        async with lane_runtime(faults=fi) as rt:
            fi.arm("fastlane.handle", rate=1.0, max_faults=2)
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            dlq = rt.naming.tenant_topic("acme", TopicNaming.DEAD_LETTER)
            for k in range(4):
                await rt.bus.produce(decoded, _measurements(32, 1000.0 + k),
                                     key="gw")
            session = rt.api("rule-processing").engine("acme").session
            # 2 records quarantined, the other 2 score through
            await wait_until(lambda: session.latency.count >= 64)
            await wait_until(
                lambda: len(list_dead_letters(rt.bus, dlq)) == 2)
            lane = rt.api("rule-processing").engine("acme").fastlane
            assert lane.status is LifecycleStatus.STARTED

    run(main())


def test_fastlane_shed_defer_and_degrade(run):
    """Flow-control routing on the fused path mirrors the slow lane:
    defer spools to the deferred topic (drained back when pressure
    clears), degrade scores via the host fallback (model_version -1)."""
    async def main():
        async with lane_runtime() as rt:
            session = rt.api("rule-processing").engine("acme").session
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            deferred = rt.naming.tenant_topic(
                "acme", TopicNaming.DEFERRED_EVENTS)
            scored_topic = rt.naming.tenant_topic(
                "acme", TopicNaming.SCORED_EVENTS)
            em = rt.api("event-management").management("acme")

            rt.flow.force_mode("acme", "defer")
            await rt.bus.produce(decoded, _measurements(32, 1000.0),
                                 key="gw")
            await wait_until(lambda: sum(
                len(r.value) for r in rt.bus.peek(deferred, limit=-1)) >= 32)
            # spooled, persisted, NOT scored
            await wait_until(lambda: em.telemetry.total_events >= 32)
            assert session.latency.count == 0
            assert rt.metrics.snapshot().get("flow.shed_defer:acme", 0) >= 32

            # pressure clears → the rule processor drains the spool back
            rt.flow.force_mode("acme", "ok")
            await wait_until(lambda: session.latency.count >= 32,
                             timeout=15.0)
            assert rt.metrics.snapshot().get(
                "flow.deferred_replayed:acme", 0) >= 32

            # degrade: host-side fallback, no XLA dispatch
            consumer = rt.bus.subscribe(scored_topic, group="lane-deg")
            rt.flow.force_mode("acme", "degrade")
            await rt.bus.produce(decoded, _measurements(32, 2000.0),
                                 key="gw")
            scored = []

            def got_fallback():
                scored.extend(r.value
                              for r in consumer.poll_nowait(max_records=64))
                return any(b.model_version == -1 for b in scored)

            await wait_until(got_fallback)
            assert rt.metrics.snapshot().get(
                "flow.shed_degrade:acme", 0) >= 32
            consumer.close()

    run(main())


# -- scoring-server coalescing (tentpole rider) ------------------------------

def test_sub_bucket_admits_coalesce(run):
    """N sub-bucket admits inside one batch window dispatch as ONE
    flush — the window, not the admit count, drives dispatch."""
    async def main():
        from sitewhere_tpu.kernel.metrics import MetricsRegistry
        from sitewhere_tpu.models import build_model
        from sitewhere_tpu.persistence.telemetry import TelemetryStore
        from sitewhere_tpu.scoring.server import ScoringConfig, ScoringSession

        session = ScoringSession(
            build_model("zscore", window=16), TelemetryStore(history=32),
            MetricsRegistry(),
            ScoringConfig(buckets=(256,), batch_window_ms=50.0))
        session.warmup()
        for k in range(5):
            session.admit(_measurements(8, 1000.0 + k, start=8 * k))
            assert not session.flush_due  # window still open, sub-bucket
        assert session.pending_n == 40
        await asyncio.sleep(0.06)  # window closes
        assert session.flush_due
        assert session.flush_nowait()
        assert session.dispatch_count == 1  # ONE dispatch for 5 admits
        await session.drain()

    run(main())


def test_single_admit_flush_is_zero_copy(run):
    """The saturation steady state (one fleet-sized admit per window)
    must not memcpy the columns through `_take_pending`."""
    async def main():
        from sitewhere_tpu.kernel.metrics import MetricsRegistry
        from sitewhere_tpu.models import build_model
        from sitewhere_tpu.persistence.telemetry import TelemetryStore
        from sitewhere_tpu.scoring.server import ScoringConfig, ScoringSession

        session = ScoringSession(
            build_model("zscore", window=16), TelemetryStore(history=32),
            MetricsRegistry(), ScoringConfig(buckets=(256,)))
        batch = _measurements(64, 1000.0)
        session.admit(batch)
        dev, val, ts, ingest, ctx, traces = session._take_pending()
        assert dev is batch.device_index  # the view, not a concat copy
        assert val is batch.value
        assert ts is batch.ts
        assert ctx is batch.ctx
        assert [(t[0], t[1]) for t in traces] == [(ctx.trace_id, 64)]
        assert session.pending_n == 0

    run(main())


# -- decoder satellite -------------------------------------------------------

def test_requests_to_batches_single_pass_equivalence():
    """The vectorized one-pass column build preserves the decoder
    contract: known tokens → columnar batches, unknown tokens →
    auto-registration, explicit registrations pass through."""
    from sitewhere_tpu.domain.batch import (
        LocationBatch,
        RegistrationBatch,
    )
    from sitewhere_tpu.services.event_sources import requests_to_batches

    ctx = BatchContext(tenant_id="t", source="s")
    table = {"a": 0, "b": 3, "c": 7}

    def resolve(tokens):
        return [table.get(t, -1) for t in tokens]

    reqs = [
        {"type": "measurement", "device": "a", "value": 1.5, "ts": 10.0},
        {"type": "measurement", "device": "ghost", "value": 2.0},
        {"type": "measurement", "device": "b", "mtype": 2, "value": 2.5,
         "ts": 11.0},
        {"type": "location", "device": "c", "lat": 33.7, "lon": -84.4,
         "ts": 12.0},
        {"type": "location", "device": "spook", "lat": 1.0, "lon": 2.0},
        {"type": "registration", "device": "new", "deviceType": "tt"},
    ]
    out = requests_to_batches(reqs, ctx, resolve)
    regs = [b for b in out if isinstance(b, RegistrationBatch)]
    meas = [b for b in out if isinstance(b, MeasurementBatch)]
    locs = [b for b in out if isinstance(b, LocationBatch)]
    assert len(meas) == 1 and len(locs) == 1 and len(regs) == 3
    assert {t for r in regs for t in r.device_tokens} == \
        {"new", "ghost", "spook"}
    m = meas[0]
    np.testing.assert_array_equal(m.device_index, [0, 3])
    np.testing.assert_array_equal(m.mtype, [0, 2])
    np.testing.assert_allclose(m.value, [1.5, 2.5])
    np.testing.assert_allclose(m.ts, [10.0, 11.0])
    loc = locs[0]
    np.testing.assert_array_equal(loc.device_index, [7])
    np.testing.assert_allclose(loc.latitude, [33.7])
    np.testing.assert_allclose(loc.longitude, [-84.4])
    np.testing.assert_allclose(loc.ts, [12.0])


def test_requests_to_batches_ignores_fields_of_unknown_devices():
    """A malformed optional field on an UNREGISTERED device's row must
    not poison the registered rows: that row only becomes a
    registration request, its value/ts are never read (regression for
    the single-pass column build)."""
    from sitewhere_tpu.domain.batch import RegistrationBatch
    from sitewhere_tpu.services.event_sources import requests_to_batches

    ctx = BatchContext(tenant_id="t", source="s")

    def resolve(tokens):
        return [{"a": 0}.get(t, -1) for t in tokens]

    reqs = [
        {"type": "measurement", "device": "a", "value": 1.5, "ts": 10.0},
        {"type": "measurement", "device": "ghost", "value": "not-a-float",
         "ts": None},
        {"type": "location", "device": "spook", "lat": "garbage"},
    ]
    out = requests_to_batches(reqs, ctx, resolve)
    meas = [b for b in out if isinstance(b, MeasurementBatch)]
    regs = [b for b in out if isinstance(b, RegistrationBatch)]
    assert len(meas) == 1 and len(regs) == 2
    np.testing.assert_allclose(meas[0].value, [1.5])
    assert {t for r in regs for t in r.device_tokens} == {"ghost", "spook"}
