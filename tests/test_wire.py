"""Process-split deployment tests (kernel/wire.py): the codec, the wire
bus with full consumer-group semantics, the control-plane ApiChannel,
and the headline check — a REAL multi-process instance (broker process +
ingest process + pipeline process) scoring simulator telemetry end to
end, the topology the reference runs as cooperating JVMs over
Kafka+gRPC [SURVEY.md §1-L3, §2.1]."""

import asyncio
import os
import subprocess
import sys

import numpy as np

from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.kernel import codec
from sitewhere_tpu.kernel.bus import EventBus
from sitewhere_tpu.kernel.wire import (
    ApiChannel,
    ApiServer,
    BusServer,
    RemoteEventBus,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_codec_roundtrip_scalars_arrays_dataclasses():
    ctx = BatchContext(tenant_id="t", source="s", trace_id=7)
    batch = MeasurementBatch(
        ctx, np.arange(5, dtype=np.uint32),
        np.zeros(5, np.uint16), np.linspace(0, 1, 5).astype(np.float32),
        np.full(5, 1700000000.0))
    from sitewhere_tpu.config import TenantConfig
    from sitewhere_tpu.domain.events import AlertLevel, DeviceAlert

    values = [None, True, False, 42, -1, 3.5, "héllo", b"\x00\xff",
              [1, [2, "x"]], {"k": 1, 2: "v"}, (1, "two"),
              np.arange(12).reshape(3, 4),
              batch,
              TenantConfig(tenant_id="acme", sections={"a": {"b": 1}}),
              DeviceAlert(level=AlertLevel.ERROR, message="hot"),
              {"action": "created",
               "tenant": TenantConfig(tenant_id="x")}]
    for v in values:
        out = codec.decode(codec.encode(v))
        if isinstance(v, np.ndarray):
            np.testing.assert_array_equal(out, v)
        elif isinstance(v, MeasurementBatch):
            np.testing.assert_array_equal(out.device_index, v.device_index)
            np.testing.assert_array_equal(out.value, v.value)
            assert out.ctx.tenant_id == "t" and out.ctx.trace_id == 7
        else:
            assert out == v, v


def test_codec_refuses_unregistered_types():
    class Evil:
        pass

    import pytest

    with pytest.raises(TypeError):
        codec.encode(Evil())
    # decode refuses unknown dataclass names (hostile peer)
    payload = bytearray(codec.encode(BatchContext(tenant_id="t")))
    payload = payload.replace(b"BatchContext", b"EvilClsNeverX")
    with pytest.raises((ValueError, KeyError)):
        codec.decode(bytes(payload))


def test_wire_bus_produce_poll_commit_rebalance(run):
    async def main():
        bus = EventBus(default_partitions=4)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port)
        await remote.initialize()

        # produce from the remote side, consume locally and remotely
        for i in range(10):
            await remote.produce("t", {"i": i}, key=f"k{i % 3}")
        c_remote = remote.subscribe("t", group="g")
        records = await c_remote.poll(max_records=100, timeout=2.0)
        assert len(records) == 10
        assert sorted(r.value["i"] for r in records) == list(range(10))
        c_remote.commit()
        await asyncio.sleep(0.05)  # commit is fire-and-forget

        # committed offsets persist across a remote consumer restart
        c_remote.close()
        await asyncio.sleep(0.05)
        await remote.produce("t", {"i": 99})
        c2 = remote.subscribe("t", group="g")
        records = await c2.poll(max_records=100, timeout=2.0)
        assert [r.value["i"] for r in records] == [99]

        # long-poll wakes on produce (not timeout)
        async def later():
            await asyncio.sleep(0.05)
            await remote.produce("t", {"i": 100})

        t = asyncio.get_running_loop().create_task(later())
        t0 = asyncio.get_running_loop().time()
        records = await c2.poll(max_records=10, timeout=5.0)
        waited = asyncio.get_running_loop().time() - t0
        await t
        assert [r.value["i"] for r in records] == [100]
        assert waited < 1.0

        # a dropped connection closes its consumers (group rebalance)
        group = bus._groups["g"]
        assert len(group.members) == 1
        remote._client.close()
        await asyncio.sleep(0.1)
        assert len(group.members) == 0
        await server.stop()

    run(main())


def test_wire_commit_pins_delivered_positions(run):
    """At-least-once across a worker SIGKILL hinges on this: a bare
    commit() must cover exactly the records DELIVERED to this process,
    never the broker-side consumer's current positions. The
    fire-and-forget commit RPC loses the wire race against the next
    poll request (which is written immediately, while the commit task
    waits a scheduler tick), so a server-positions commit would cover
    the new in-flight batch — and a process killed while handling it
    would never see those records again (the fleet kill drill measured
    exactly one poll batch lost per killed consumer this way)."""

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port)
        await remote.initialize()
        await remote.produce("t", {"i": 0})
        await remote.produce("t", {"i": 1})
        consumer = remote.subscribe("t", group="g")
        first = await consumer.poll(max_records=1, timeout=2.0)
        assert [r.value["i"] for r in first] == [0]
        # the consuming loop's shape: commit what was processed, then
        # immediately poll the next batch — the poll request reaches
        # the broker before the spawned commit RPC does
        consumer.commit()
        second = await consumer.poll(max_records=1, timeout=2.0)
        assert [r.value["i"] for r in second] == [1]
        await asyncio.sleep(0.1)  # let the commit RPC land (after poll)
        # "kill" the worker mid-batch: record {i: 1} was delivered but
        # never committed — a successor in the group MUST see it again
        consumer.close()
        await asyncio.sleep(0.05)
        successor = remote.subscribe("t", group="g")
        redelivered = await successor.poll(max_records=10, timeout=2.0)
        assert [r.value["i"] for r in redelivered] == [1], (
            "commit covered an undelivered in-flight batch")
        successor.close()
        await remote.stop()
        await server.stop()

    run(main())


def test_wire_fencing_produce_and_commit(run):
    """Epoch fencing over the wire (docs/FLEET.md): a stale-epoch
    produce raises the DISTINCT FencedError client-side (typed, with
    the tenant attached — the worker's 'stop engines, do not retry'
    signal), and a fire-and-forget stale commit both leaves the group
    offsets untouched broker-side AND surfaces through the client's
    on_fenced callback."""
    from sitewhere_tpu.kernel.bus import FencedError

    async def main():
        bus = EventBus(default_partitions=1)
        server = BusServer(bus)
        await server.start()
        remote = RemoteEventBus("127.0.0.1", server.port)
        await remote.initialize()
        fenced_tenants = []
        # the callback receives (tenant, rejected-token epoch) so the
        # worker can ignore stale rejections of superseded grants
        remote.on_fenced = lambda tenant, epoch: fenced_tenants.append(
            (tenant, epoch))

        ctl = "wx.instance.fleet-control"
        topic = "wx.tenant.t0.inbound-events"
        # epoch 1 places t0 on w0; epoch 2 moves it to w1 with w0 DEAD
        # (absent from the live list) — w0's writes must reject NOW
        await remote.produce(ctl, {"kind": "placement", "epoch": 1,
                                   "assignment": {"t0": "w0"},
                                   "workers": ["w0", "w1"]})
        await remote.produce(topic, {"n": 1}, fence=["t0", 1, "w0"])
        await remote.produce(ctl, {"kind": "placement", "epoch": 2,
                                   "assignment": {"t0": "w1"},
                                   "workers": ["w1"]})
        try:
            await remote.produce(topic, {"n": 2}, fence=["t0", 1, "w0"])
            raise AssertionError("stale-epoch produce was accepted")
        except FencedError as exc:
            assert exc.tenant == "t0"
        # the new owner writes fine
        await remote.produce(topic, {"n": 3}, fence=["t0", 2, "w1"])

        # stale fire-and-forget commit: rejected broker-side, reported
        # through on_fenced (no caller awaits the RPC)
        consumer = remote.subscribe(topic, group="t0.inbound-processing")
        records = await consumer.poll(max_records=10, timeout=2.0)
        assert len(records) == 2
        consumer.commit(fence=["t0", 1, "w0"])
        deadline = asyncio.get_event_loop().time() + 5.0
        while not fenced_tenants \
                and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.02)
        assert fenced_tenants == [("t0", 1)]
        assert not bus._groups["t0.inbound-processing"].committed, (
            "a fenced commit moved group offsets")
        # the owner's commit lands
        consumer.commit(fence=["t0", 2, "w1"])
        await asyncio.sleep(0.2)
        assert bus._groups["t0.inbound-processing"].committed
        assert bus.fences.rejections >= 2
        consumer.close()
        await remote.stop()
        await server.stop()

    run(main())


def test_api_channel_engine_calls(run):
    """Control plane: a peer resolves an engine and calls its methods
    (numpy in/out) over the wire, with wait-for-engine semantics."""

    async def main():
        from sitewhere_tpu.config import InstanceSettings, TenantConfig
        from sitewhere_tpu.domain.model import DeviceType
        from sitewhere_tpu.kernel.service import ServiceRuntime
        from sitewhere_tpu.services import DeviceManagementService

        rt = ServiceRuntime(InstanceSettings(instance_id="api-test"))
        rt.add_service(DeviceManagementService(rt))
        await rt.start()
        await rt.add_tenant(TenantConfig(tenant_id="acme"))
        dm = rt.api("device-management").management("acme")
        dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 10)

        server = ApiServer(rt)
        await server.start()
        channel = ApiChannel("127.0.0.1", server.port)
        await channel.wait_engine("device-management", "acme", timeout=5.0)
        proxy_mask = await channel.call(
            "device-management", "registered_mask", tenant="acme",
            args=[np.asarray([0, 5, 42], np.uint32)])
        np.testing.assert_array_equal(proxy_mask, [True, True, False])
        device = await channel.call("device-management",
                                    "get_device_by_token",
                                    tenant="acme", args=["dev-3"])
        assert device.token == "dev-3"
        # private methods refused
        import pytest

        with pytest.raises(RuntimeError, match="not exposed"):
            await channel.call("device-management", "_do_start",
                              tenant="acme")
        channel.close()
        await server.stop()
        await rt.stop()

    run(main())


INGEST_PROC = r'''
import asyncio, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")

async def main():
    from sitewhere_tpu.config import InstanceSettings
    from sitewhere_tpu.kernel.service import ServiceRuntime
    from sitewhere_tpu.kernel.wire import RemoteEventBus
    from sitewhere_tpu.services import EventSourcesService

    bus_port = int(sys.argv[1])
    rt = ServiceRuntime(InstanceSettings(instance_id="split"),
                        bus=RemoteEventBus("127.0.0.1", bus_port))
    rt.add_service(EventSourcesService(rt))
    await rt.start()
    print("INGEST-UP", flush=True)
    # tenant broadcast arrives over the SHARED bus from the pipeline proc;
    # wait for our engine, then feed simulator payloads through the
    # receiver exactly like a gateway would
    from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

    eng = await rt.wait_for_engine("event-sources", "acme", timeout=60.0)
    receiver = eng.receiver("default")
    sim = DeviceSimulator(SimConfig(num_devices=50, seed=3), tenant_id="acme")
    for k in range(40):
        payload, _ = sim.payload(t=60.0 * k)
        await receiver.submit(payload)
    await asyncio.sleep(3.0)   # let the queue drain through decode+produce
    await rt.stop()
    print("INGEST-DONE", flush=True)

asyncio.run(main())
'''


def test_three_process_instance_scores_end_to_end(run):
    """THE process-split check: broker thread (wire bus) + ingest OS
    process (event-sources only) + pipeline runtime (device-mgmt,
    inbound, event-mgmt, device-state) sharing one instance: telemetry
    decoded in one process is masked/persisted in another."""

    async def main():
        from sitewhere_tpu.config import InstanceSettings, TenantConfig
        from sitewhere_tpu.domain.model import DeviceType
        from sitewhere_tpu.kernel.service import ServiceRuntime
        from sitewhere_tpu.services import (
            DeviceManagementService,
            DeviceStateService,
            EventManagementService,
            InboundProcessingService,
        )

        # broker: in this process but a REAL wire server (sockets)
        broker_bus = EventBus(default_partitions=4)
        await broker_bus.initialize()
        await broker_bus.start()
        broker = BusServer(broker_bus)
        await broker.start()

        # pipeline runtime attaches to the broker over the wire too —
        # every record in this test crosses a socket
        rt = ServiceRuntime(InstanceSettings(instance_id="split"),
                            bus=RemoteEventBus("127.0.0.1", broker.port))
        for cls in (DeviceManagementService, InboundProcessingService,
                    EventManagementService, DeviceStateService):
            rt.add_service(cls(rt))
        await rt.start()
        await rt.add_tenant(TenantConfig(tenant_id="acme"))
        dm = rt.api("device-management").management("acme")
        dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 50)

        # ingest process: separate interpreter, event-sources only
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c",
             INGEST_PROC.replace("@REPO@", REPO), str(broker.port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        try:
            em = rt.api("event-management").management("acme")
            deadline = asyncio.get_running_loop().time() + 120.0
            while em.telemetry.total_events < 50 * 40:
                await asyncio.sleep(0.2)
                assert asyncio.get_running_loop().time() < deadline, (
                    f"stalled at {em.telemetry.total_events} events; "
                    f"ingest rc={proc.poll()}")
            state = rt.api("device-state").state("acme").get_state(7)
            assert state["last_seen"] > 0
            out, err = proc.communicate(timeout=60)
            assert proc.returncode == 0, err.decode()[-2000:]
            assert b"INGEST-DONE" in out
        finally:
            if proc.poll() is None:
                proc.kill()
        await rt.stop()
        await broker.stop()
        await broker_bus.stop()

    run(main())


def test_codec_rejects_wire_name_collision():
    """Two different classes under one wire name would make decode
    construct the wrong type; registration must fail loudly instead."""
    import dataclasses

    import pytest

    @dataclasses.dataclass
    class CollideMe:
        x: int = 0

    codec.register_class(CollideMe)
    try:
        # same name, different class object → loud failure
        @dataclasses.dataclass
        class CollideMe:  # noqa: F811
            y: str = ""

        with pytest.raises(ValueError, match="collision"):
            codec.register_class(CollideMe)
    finally:
        codec._CLASSES.pop("CollideMe", None)


def test_api_server_blocks_private_sub_accessor(run):
    """The '_'-guard on method names must also cover the `sub` accessor
    (advisor round-3: sub='_pending' reached private state)."""

    async def main():
        from sitewhere_tpu.kernel.wire import ApiServer

        class FakeService:
            def api(self):
                return self

            def ping(self):
                return "pong"

        class FakeRuntime:
            services = {"svc": FakeService()}

        server = ApiServer(FakeRuntime(), host="127.0.0.1", port=0)
        ok = await server._op_call(
            {"identifier": "svc", "method": "ping"})
        assert ok == "pong"
        import pytest

        with pytest.raises(PermissionError):
            await server._op_call(
                {"identifier": "svc", "method": "ping", "sub": "_secret"})
        with pytest.raises(PermissionError):
            await server._op_call(
                {"identifier": "svc", "method": "_private"})

    run(main())


def test_wire_shared_secret_auth(run):
    """With a secret configured, the broker serves only peers whose
    FIRST frame authenticates; wrong/missing secrets are cut off. The
    client handshakes transparently, so the whole RemoteEventBus
    surface works unchanged over an authed broker."""

    async def main():
        from sitewhere_tpu.kernel.wire import (
            BusServer,
            RemoteEventBus,
            WireClient,
        )

        backing = EventBus(default_partitions=2)
        await backing.initialize()
        await backing.start()
        server = BusServer(backing, secret="s3cret")
        await server.start()
        try:
            # right secret: full surface works
            remote = RemoteEventBus("127.0.0.1", server.port,
                                    secret="s3cret")
            await remote.initialize()
            await remote.produce("t", {"x": 1}, key="k")
            c = remote.subscribe("t", group="g")
            records = await c.poll(max_records=10, timeout=5.0)
            assert [r.value for r in records] == [{"x": 1}]
            await remote.stop()

            # wrong secret: the handshake call itself fails
            bad = WireClient("127.0.0.1", server.port, secret="nope")
            import pytest

            with pytest.raises((RuntimeError, ConnectionError)):
                await bad.connect()
            bad.close()

            # no secret at all: first (non-auth) op is rejected/cut off
            anon = WireClient("127.0.0.1", server.port)
            await anon.connect()
            with pytest.raises((RuntimeError, ConnectionError)):
                await asyncio.wait_for(anon.call("topic_names"), 5.0)
            anon.close()
        finally:
            await server.stop()
            await backing.stop()

    run(main())
