"""Sharded egress fast lanes (kernel/egresslane.py): ISSUE 6's
acceptance tests.

- wiring/config: the fused egress stage engages by default, the tenant
  `egress: {fused, lanes}` section pins it either way, and `lanes`
  shards BOTH the egress stage and the consumer lanes.
- lane-count equivalence: `lanes=1` vs `lanes=4` runs of the same event
  sequence produce identical scored events, persisted telemetry,
  alerts, and committed offsets — shard count changes concurrency,
  never behavior.
- egress-fusion equivalence: fused vs legacy-inline sink produce
  identical outputs (the A/B lever measures speed, not semantics).
- alert emission off the flush path: counted (`rules.alerts_emitted`),
  and an alert-path failure can never block a scoring flush.
- chaos: `egress.publish` faults quarantine the scored batch to the
  tenant DLQ with egress provenance (replayable onto the scored
  topic); crash faults on the sharded consumer loops are healed by the
  supervisor and the pipeline still drains.
"""

import asyncio
import contextlib

import numpy as np

from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.kernel.bus import TopicNaming
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.services import (
    DeviceManagementService,
    DeviceStateService,
    EventManagementService,
    EventSourcesService,
    InboundProcessingService,
    RuleProcessingService,
)
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig
from tests.test_pipeline import wait_until

RULE = {"model": "zscore", "model_config": {"window": 16},
        "threshold": 6.0, "batch_window_ms": 1.0,
        "buckets": [256], "capacity": 256}


@contextlib.asynccontextmanager
async def egress_runtime(num_devices=32, fastlane=None, egress=None,
                         faults=None, instance_id="eg"):
    """Full pipeline runtime with tenant 'acme'; `egress` is the tenant
    `egress:` section ({fused, lanes}), `fastlane` pins the ingress
    lane via its override (None = auto-detection)."""
    rt = ServiceRuntime(InstanceSettings(instance_id=instance_id))
    for cls in (DeviceManagementService, EventSourcesService,
                InboundProcessingService, EventManagementService,
                DeviceStateService, RuleProcessingService):
        rt.add_service(cls(rt))
    if faults is not None:
        rt.install_faults(faults)
    await rt.start()
    sections = {"rule-processing": dict(RULE)}
    if fastlane is not None:
        sections["fastlane"] = {"enabled": fastlane}
    if egress is not None:
        sections["egress"] = dict(egress)
    await rt.add_tenant(TenantConfig(tenant_id="acme", sections=sections))
    dm = rt.api("device-management").management("acme")
    dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), num_devices)
    session = rt.api("rule-processing").engine("acme").session
    await wait_until(lambda: session.ready, timeout=60.0)
    try:
        yield rt
    finally:
        await rt.stop()


def _measurements(n: int, t: float, start: int = 0,
                  value: float = 21.0) -> MeasurementBatch:
    return MeasurementBatch(
        BatchContext(tenant_id="acme", source="test"),
        np.arange(start, start + n, dtype=np.uint32),
        np.zeros(n, np.uint16), np.full(n, value, np.float32),
        np.full(n, t))


async def _drive(rt, n_sim=48, ticks=12, anomaly_rate=0.05):
    """Feed `ticks` simulator payloads and return the run's observable
    outputs: scored {(device, ts) -> (score, anomaly)}, telemetry
    total, alert set, and the decoded-topic group's committed offsets
    (summed per partition) once everything has drained and committed."""
    scored_topic = rt.naming.tenant_topic("acme", TopicNaming.SCORED_EVENTS)
    consumer = rt.bus.subscribe(scored_topic, group="egress-test-meter")
    sim = DeviceSimulator(SimConfig(num_devices=n_sim, seed=11,
                                    anomaly_rate=anomaly_rate,
                                    anomaly_magnitude=15.0),
                          tenant_id="acme")
    receiver = rt.api("event-sources").engine("acme").receiver("default")
    for k in range(ticks):
        payload, _ = sim.payload(t=1000.0 + 60.0 * k)
        assert await receiver.submit(payload)
    expected = 32 * ticks  # only the registered 32 of n_sim score
    em = rt.api("event-management").management("acme")
    await wait_until(lambda: em.telemetry.total_events >= expected,
                     timeout=30.0)
    scored = {}

    def collect():
        for r in consumer.poll_nowait(max_records=512):
            b = r.value
            for i in range(len(b)):
                scored[(int(b.device_index[i]), float(b.ts[i]))] = (
                    round(float(b.score[i]), 3), bool(b.is_anomaly[i]))
        return len(scored) >= expected

    await wait_until(collect, timeout=30.0)
    consumer.close()
    # device_id is a per-run UUID; the bootstrap token (`dev-{i}`) is
    # the stable cross-run identity
    dm = rt.api("device-management").management("acme")
    alerts = {(dm.get_device(a.device_id).token, float(a.event_date),
               a.type, a.message) for a in em.spi.alerts}
    # the decoded-topic group commits via the shared checkpoint barrier
    # once everything settled AND published; wait for it to catch up
    decoded = rt.naming.tenant_topic("acme",
                                     TopicNaming.EVENT_SOURCE_DECODED)
    end_total = sum(rt.bus.end_offsets(decoded))
    group = rt.bus._groups["acme.inbound-processing"]

    def committed_total():
        return sum(off for (topic, _p), off in group.committed.items()
                   if topic == decoded)

    await wait_until(lambda: committed_total() >= end_total, timeout=30.0)
    return scored, em.telemetry.total_events, alerts, committed_total()


# -- wiring / config --------------------------------------------------------

def test_egress_wiring_and_lane_config(run):
    async def main():
        # fused by default, 1 lane; session sink IS the stage
        async with egress_runtime(instance_id="eg-w1") as rt:
            eng = rt.api("rule-processing").engine("acme")
            assert eng.egress is not None and eng.egress.lanes == 1
            assert eng.session.sink is eng.egress
            assert len(eng.fastlanes) == 1
        # lanes=4 shards the egress stage AND the ingress fast lane;
        # every shard loop is a supervised child of the engine
        async with egress_runtime(egress={"lanes": 4},
                                  instance_id="eg-w4") as rt:
            eng = rt.api("rule-processing").engine("acme")
            assert eng.egress.lanes == 4
            assert len(eng.egress.shards) == 4
            assert len(eng.fastlanes) == 4
            assert len({lane.name for lane in eng.fastlanes}) == 4
        # fused: false pins the legacy inline sink (the A/B baseline)
        async with egress_runtime(egress={"fused": False},
                                  instance_id="eg-wo") as rt:
            eng = rt.api("rule-processing").engine("acme")
            assert eng.egress is None
            assert eng.session.sink == eng._deliver_scored
        # lanes also shard the STAGED lane's consumers
        async with egress_runtime(fastlane=False, egress={"lanes": 3},
                                  instance_id="eg-ws") as rt:
            inb = rt.services["inbound-processing"].engines["acme"]
            assert len(inb.processors) == 3
            emg = rt.services["event-management"].engines["acme"]
            assert len(emg.persisters) == 3

    run(main())


# -- equivalence ------------------------------------------------------------

def test_lane_count_equivalence(run):
    """lanes=1 vs lanes=4: identical scored events, persisted
    telemetry, alerts, and committed offsets — sharding changes
    concurrency, never behavior."""
    async def main():
        async with egress_runtime(egress={"lanes": 1},
                                  instance_id="eg-l1") as rt:
            one = await _drive(rt)
        async with egress_runtime(egress={"lanes": 4},
                                  instance_id="eg-l4") as rt:
            four = await _drive(rt)
        scored_1, total_1, alerts_1, committed_1 = one
        scored_4, total_4, alerts_4, committed_4 = four
        assert total_1 == total_4 == 32 * 12
        assert scored_1.keys() == scored_4.keys()
        assert len(scored_1) == 32 * 12
        for key, val in scored_1.items():
            assert scored_4[key] == val, key
        assert alerts_1 == alerts_4 and alerts_1  # anomalies exist
        assert committed_1 == committed_4 > 0

    run(main())


def test_egress_fusion_equivalence(run):
    """Fused egress vs the legacy inline sink: identical outputs (the
    bench A/B lever changes the mechanism, not the results)."""
    async def main():
        async with egress_runtime(egress={"fused": True, "lanes": 2},
                                  instance_id="eg-on") as rt:
            fused = await _drive(rt)
            snap = rt.metrics.snapshot()
            assert snap.get("egress.publish_failures", 0) == 0
        async with egress_runtime(egress={"fused": False},
                                  instance_id="eg-off") as rt:
            inline = await _drive(rt)
        assert fused[0] == inline[0]
        assert fused[1] == inline[1]
        assert fused[2] == inline[2]
        assert fused[3] == inline[3]

    run(main())


# -- alert emission off the flush path --------------------------------------

def test_alerts_emitted_off_flush_path_and_counted(run):
    async def main():
        async with egress_runtime(instance_id="eg-al") as rt:
            session = rt.api("rule-processing").engine("acme").session
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            # zscore needs min_history (8) observations per device
            # before it scores; warm with flat values, then one clearly
            # anomalous batch
            for k in range(8):
                await rt.bus.produce(decoded,
                                     _measurements(32, 1000.0 + 60 * k),
                                     key="gw")
            await rt.bus.produce(decoded,
                                 _measurements(32, 2000.0, value=900.0),
                                 key="gw")
            em = rt.api("event-management").management("acme")
            await wait_until(lambda: len(em.spi.alerts) >= 32, timeout=15.0)
            assert rt.metrics.snapshot().get("rules.alerts_emitted",
                                             0) >= 32
            assert session.latency.count >= 32 * 9

    run(main())


def test_alert_path_failure_never_blocks_scoring(run):
    """An alert-store failure is counted and isolated: scoring flushes
    and scored publishes keep flowing (the satellite-1 guarantee)."""
    async def main():
        async with egress_runtime(instance_id="eg-ab") as rt:
            em = rt.api("event-management").management("acme")

            def boom(batch):
                raise RuntimeError("alert store down")

            em.spi.add_alert_batch = boom
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            scored_topic = rt.naming.tenant_topic(
                "acme", TopicNaming.SCORED_EVENTS)
            consumer = rt.bus.subscribe(scored_topic, group="eg-ab-meter")
            # 8 warm batches (zscore min_history), then anomalous ones
            # that force the alert path on every flush
            for k in range(8):
                await rt.bus.produce(decoded,
                                     _measurements(32, 1000.0 + 60 * k),
                                     key="gw")
            for k in range(3):
                await rt.bus.produce(
                    decoded, _measurements(32, 2000.0 + 60 * k,
                                           value=900.0), key="gw")
            seen = 0

            def drained():
                nonlocal seen
                seen += sum(len(r.value) for r in
                            consumer.poll_nowait(max_records=64))
                return seen >= 32 * 11

            await wait_until(drained, timeout=15.0)
            assert rt.metrics.snapshot().get("egress.alert_failures",
                                             0) > 0
            consumer.close()

    run(main())


def test_egress_backlog_is_bounded_and_drains(run):
    """A slow (not failing) publish path surfaces as backpressure —
    `backlogged` through the commit barrier, pausing the consumer —
    never as an unbounded in-memory queue; when the path clears, the
    backlog drains and every batch publishes."""
    async def main():
        async with egress_runtime(instance_id="eg-bp") as rt:
            eng = rt.api("rule-processing").engine("acme")
            egress = eng.egress
            gate = asyncio.Event()
            slow_calls = 0

            async def slow_produce(topic, value, key=None, **kw):
                nonlocal slow_calls
                slow_calls += 1
                await gate.wait()
                return rt.bus.produce_nowait(topic, value, key=key)

            # force the shard path (no sync fast path) onto a publish
            # that stalls until released (instance attribute shadows
            # the method — the shard resolves bus.produce per call)
            egress._produce_nowait = None
            rt.bus.produce = slow_produce
            try:
                cap = egress.MAX_BACKLOG_PER_SHARD * egress.lanes
                for k in range(cap + 8):
                    egress.submit(_scored(eng, 4, 1000.0 + k))
                await asyncio.sleep(0.05)
                assert egress.backlogged
                from sitewhere_tpu.kernel.egresslane import EgressBarrier
                barrier = EgressBarrier(eng.session, egress)
                assert barrier.backlogged  # the consumer-loop pause view
                assert barrier.settled_through == -1  # offsets held
            finally:
                gate.set()
            await egress.drain(timeout=15.0)
            del rt.bus.produce  # restore the real method for teardown
            assert egress.idle and not egress.backlogged
            assert rt.metrics.snapshot().get(
                "egress.publish_failures", 0) == 0

    run(main())


def _scored(eng, n, t):
    from sitewhere_tpu.domain.batch import ScoredBatch
    return ScoredBatch(
        BatchContext(tenant_id="acme", source="gw"),
        np.arange(n, dtype=np.uint32), np.zeros(n, np.float32),
        np.zeros(n, bool), np.full(n, t))


# -- chaos on the egress stage and the sharded loops ------------------------

def test_egress_publish_chaos_quarantine_and_replay(run):
    """`egress.publish` faults: the scored batch is quarantined to the
    tenant DLQ with egress provenance — and a DLQ replay re-produces it
    onto the scored topic (nothing is ever silently dropped)."""
    async def main():
        from sitewhere_tpu.kernel.dlq import (
            list_dead_letters,
            replay_dead_letters,
        )
        from sitewhere_tpu.kernel.faults import FaultInjector
        from sitewhere_tpu.kernel.lifecycle import LifecycleStatus

        fi = FaultInjector(seed=3)
        async with egress_runtime(faults=fi, instance_id="eg-ch") as rt:
            eng = rt.api("rule-processing").engine("acme")
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            dlq = rt.naming.tenant_topic("acme", TopicNaming.DEAD_LETTER)
            scored_topic = rt.naming.tenant_topic(
                "acme", TopicNaming.SCORED_EVENTS)
            fi.arm("egress.publish", rate=1.0, max_faults=1)
            await rt.bus.produce(decoded, _measurements(32, 1000.0),
                                 key="gw")
            await wait_until(
                lambda: len(list_dead_letters(rt.bus, dlq)) >= 1,
                timeout=15.0)
            entries = list_dead_letters(rt.bus, dlq)
            assert len(entries) == 1
            assert "egress" in entries[0][1]["stage"]
            assert entries[0][1]["original_topic"] == scored_topic
            assert isinstance(entries[0][1]["value"].score, np.ndarray)
            snap = rt.metrics.snapshot()
            assert snap.get("egress.publish_failures", 0) == 1
            # the shard survived the injected fault (quarantine, not
            # crash) and later batches publish normally
            assert eng.egress.shards[0].status is LifecycleStatus.STARTED
            consumer = rt.bus.subscribe(scored_topic, group="eg-ch-meter")
            await rt.bus.produce(decoded, _measurements(32, 1060.0),
                                 key="gw")
            seen = []

            def events_seen(at_least):
                def check():
                    seen.extend(consumer.poll_nowait(max_records=64))
                    return sum(len(r.value) for r in seen) >= at_least
                return check

            await wait_until(events_seen(32), timeout=15.0)
            # replay the quarantined batch back onto the scored topic
            n = await replay_dead_letters(rt.bus, dlq,
                                          metrics=rt.metrics)
            assert n == 1
            await wait_until(events_seen(64), timeout=15.0)
            consumer.close()

    run(main())


def test_sharded_loops_survive_crash_faults(run):
    """Crash faults on the sharded consumer loops: the supervisor
    restarts them (restart counters move), no shard wedges, and the
    full sequence still scores and publishes exactly once per
    delivery."""
    async def main():
        from sitewhere_tpu.kernel.faults import FaultInjector
        from sitewhere_tpu.kernel.lifecycle import LifecycleStatus

        fi = FaultInjector(seed=7)
        async with egress_runtime(egress={"lanes": 4}, faults=fi,
                                  instance_id="eg-sv") as rt:
            eng = rt.api("rule-processing").engine("acme")
            decoded = rt.naming.tenant_topic(
                "acme", TopicNaming.EVENT_SOURCE_DECODED)
            scored_topic = rt.naming.tenant_topic(
                "acme", TopicNaming.SCORED_EVENTS)
            consumer = rt.bus.subscribe(scored_topic, group="eg-sv-meter")
            fi.arm("bus.poll", rate=0.05, max_faults=6)
            for k in range(12):
                await rt.bus.produce(decoded,
                                     _measurements(32, 1000.0 + 60 * k),
                                     key=f"gw{k}")
            seen = 0

            def drained():
                nonlocal seen
                from sitewhere_tpu.kernel.faults import FaultInjected
                try:
                    records = consumer.poll_nowait(max_records=128)
                except FaultInjected:
                    return False  # the armed site hit OUR meter poll
                seen += sum(len(r.value) for r in records)
                return seen >= 12 * 32

            await wait_until(drained, timeout=30.0)
            fi.disarm()
            restarts = rt.metrics.counter("supervisor.restarts").value
            assert restarts > 0  # crashes happened and were healed
            await wait_until(lambda: all(
                lane.status is LifecycleStatus.STARTED
                for lane in eng.fastlanes), timeout=15.0)
            assert all(sh.status is LifecycleStatus.STARTED
                       for sh in eng.egress.shards)
            consumer.close()

    run(main())
