"""Test environment: force JAX onto CPU with 8 virtual devices.

Per SURVEY.md §4 (rebuild test strategy): TPU tests run identically on CPU
via a host-platform device mesh, so sharding/pjit tests exercise real
multi-device semantics without TPU hardware. Must run before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def run():
    """Run a coroutine on a fresh event loop (sync test driver)."""

    def _run(coro):
        return asyncio.run(coro)

    return _run
