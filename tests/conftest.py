"""Test environment: force JAX onto CPU with 8 virtual devices.

Per SURVEY.md §4 (rebuild test strategy): TPU tests run identically on CPU
via a host-platform device mesh, so sharding/pjit tests exercise real
multi-device semantics without TPU hardware. Must run before jax import.
"""

import os

# hard override: the image pins JAX_PLATFORMS=axon (the tunneled TPU) and
# re-asserts it at interpreter startup, so setdefault is not enough and the
# jax.config update below is what actually sticks.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import asyncio  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, (
    f"test mesh wants 8 virtual CPU devices, got {jax.devices()}")


@pytest.fixture
def run():
    """Run a coroutine on a fresh event loop (sync test driver)."""

    def _run(coro):
        return asyncio.run(coro)

    return _run
