"""Durable event store + entity snapshots (persistence/durable.py).

The reference's event-management component persists to a durable store
(Mongo/InfluxDB/Cassandra, [SURVEY.md §2.2]) and treats it as the
recovery source of truth ([SURVEY.md §5.4]). These tests pin the rebuilt
contract: segment framing + torn-tail truncation, spill tee + replay,
registry snapshot round-trip, and a real kill -9 chaos test in which a
restarted process recovers history, registrations, and scoring.
"""

import asyncio
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from sitewhere_tpu.domain.batch import BatchContext, MeasurementBatch
from sitewhere_tpu.domain.events import DeviceAlert
from sitewhere_tpu.persistence.durable import (
    RT_COLD,
    RT_MEASUREMENTS,
    DurableEventLog,
    SegmentLog,
    load_snapshot,
    save_snapshot,
)
from sitewhere_tpu.persistence.memory import (
    InMemoryDeviceEventManagement,
    InMemoryDeviceManagement,
)
from sitewhere_tpu.domain.model import (
    Device,
    DeviceAssignment,
    DeviceGroup,
    DeviceGroupElement,
    DeviceType,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_batch(n=16, base=0.0):
    ctx = BatchContext(tenant_id="acme", source="test")
    return MeasurementBatch(
        ctx,
        device_index=np.arange(n, dtype=np.uint32),
        mtype=np.zeros(n, np.uint16),
        value=(np.arange(n) + base).astype(np.float32),
        ts=np.full(n, 1000.0 + base, np.float64),
    )


# ---------------------------------------------------------------------------
# SegmentLog framing
# ---------------------------------------------------------------------------

class TestSegmentLog:
    def test_round_trip(self, tmp_path):
        log = SegmentLog(str(tmp_path))
        payloads = [bytes([i]) * (i + 1) for i in range(10)]
        for i, p in enumerate(payloads):
            log.append(i % 3 + 1, p)
        log.close()
        out = list(SegmentLog(str(tmp_path)).replay())
        assert [bytes(p) for _, p in out] == payloads
        assert [t for t, _ in out] == [i % 3 + 1 for i in range(10)]

    def test_rotation_and_order(self, tmp_path):
        log = SegmentLog(str(tmp_path), segment_bytes=256)
        for i in range(50):
            log.append(1, f"rec-{i:04d}".encode() * 4)
        log.close()
        segs = log._segments()
        assert len(segs) > 1  # rotated
        recs = [bytes(p) for _, p in SegmentLog(str(tmp_path)).replay()]
        assert recs == [f"rec-{i:04d}".encode() * 4 for i in range(50)]

    def test_prune_keeps_newest(self, tmp_path):
        log = SegmentLog(str(tmp_path), segment_bytes=128, max_segments=3)
        for i in range(100):
            log.append(1, f"{i:06d}".encode() * 3)
        log.close()
        assert len(log._segments()) <= 4  # 3 sealed + active
        recs = [bytes(p) for _, p in SegmentLog(str(tmp_path)).replay()]
        # oldest pruned, newest survive, order preserved
        assert recs[-1] == b"000099" * 3
        nums = [int(r[:6]) for r in recs]
        assert nums == sorted(nums)

    def test_torn_tail_truncated(self, tmp_path):
        log = SegmentLog(str(tmp_path))
        log.append(1, b"good-record")
        log.append(1, b"second-good")
        log.close()
        seg = log._segments()[-1][1]
        with open(seg, "ab") as f:
            f.write(b"\x40\x00\x00\x00\xde\xad\xbe\xef\x01torn")  # lies
        recs = [bytes(p) for _, p in SegmentLog(str(tmp_path)).replay()]
        assert recs == [b"good-record", b"second-good"]

    def test_crc_corruption_truncates(self, tmp_path):
        log = SegmentLog(str(tmp_path))
        log.append(1, b"aaaa")
        log.append(1, b"bbbb")
        log.close()
        seg = log._segments()[-1][1]
        data = bytearray(open(seg, "rb").read())
        data[-1] ^= 0xFF  # flip a payload byte of the second record
        open(seg, "wb").write(bytes(data))
        recs = [bytes(p) for _, p in SegmentLog(str(tmp_path)).replay()]
        assert recs == [b"aaaa"]

    def test_new_writer_appends_new_segment(self, tmp_path):
        log = SegmentLog(str(tmp_path))
        log.append(1, b"first-life")
        log.close()
        log2 = SegmentLog(str(tmp_path))
        log2.append(1, b"second-life")
        log2.close()
        recs = [bytes(p) for _, p in SegmentLog(str(tmp_path)).replay()]
        assert recs == [b"first-life", b"second-life"]


# ---------------------------------------------------------------------------
# DurableEventLog (threaded tee) + SPI replay
# ---------------------------------------------------------------------------

class TestDurableEventLog:
    def test_submit_encode_replay(self, tmp_path):
        dlog = DurableEventLog(str(tmp_path))
        batch = mk_batch(8)
        alert = DeviceAlert(device_id="d1", message="hot")
        dlog.submit(RT_MEASUREMENTS, batch)
        dlog.submit(RT_COLD, alert)
        dlog.close()
        assert dlog.written == 2 and dlog.dropped == 0
        got = []
        DurableEventLog(str(tmp_path)).replay(
            lambda t, p: got.append((t, bytes(p))))
        assert [t for t, _ in got] == [RT_MEASUREMENTS, RT_COLD]
        dec = MeasurementBatch.decode(got[0][1],
                                      BatchContext(tenant_id="acme"))
        np.testing.assert_array_equal(dec.value, batch.value)

    def test_spi_tee_and_replay(self, tmp_path):
        dm = InMemoryDeviceManagement()
        em = InMemoryDeviceEventManagement(
            dm, history=64, durable=DurableEventLog(str(tmp_path)))
        for k in range(5):
            em.add_measurements(mk_batch(16, base=k * 100.0))
        em.add_alerts([DeviceAlert(device_id="d0", message="boom")])
        em.durable.close()

        # second life: same dir, fresh stores
        em2 = InMemoryDeviceEventManagement(
            InMemoryDeviceManagement(), history=64,
            durable=DurableEventLog(str(tmp_path)))
        assert em2.telemetry.total_events == 5 * 16
        w, valid = em2.telemetry.window(np.arange(16), 5)
        # per-device window = the 5 appended values in order
        np.testing.assert_allclose(w[3], [3, 103, 203, 303, 403])
        assert valid.all()
        assert em2.alerts[0].message == "boom"
        # replay does not re-log: the log still holds exactly 6 records
        n = sum(1 for _ in em2.durable.log.replay())
        em2.durable.close()
        assert n == 6


# ---------------------------------------------------------------------------
# Registry snapshots
# ---------------------------------------------------------------------------

class TestRegistrySnapshot:
    def test_round_trip(self, tmp_path):
        dm = InMemoryDeviceManagement()
        dt = dm.create_device_type(DeviceType(token="thermo", name="T"))
        devs = [dm.create_device(Device(token=f"d{i}",
                                        device_type_id=dt.id))
                for i in range(10)]
        dm.create_device_assignment(
            DeviceAssignment(device_id=devs[0].id, token="a0"))
        g = dm.create_device_group(DeviceGroup(token="g1", name="G"))
        dm.add_device_group_elements(
            g.id, [DeviceGroupElement(device_id=devs[1].id)])
        path = str(tmp_path / "registry.snap")
        save_snapshot(path, dm.to_snapshot())

        dm2 = InMemoryDeviceManagement()
        dm2.restore_snapshot(load_snapshot(path))
        assert dm2.device_count() == 10
        assert dm2.get_device_by_token("d3").index == devs[3].index
        assert dm2.get_device_by_index(devs[3].index).token == "d3"
        assert len(dm2.get_active_assignments_for_device(devs[0].id)) == 1
        assert dm2.expand_group_devices(g.id)[0].token == "d1"
        # index counter advanced past restored devices: no index reuse
        d_new = dm2.create_device(Device(token="new",
                                         device_type_id=dt.id))
        assert d_new.index == 10

    def test_corrupt_snapshot_ignored(self, tmp_path):
        path = str(tmp_path / "registry.snap")
        save_snapshot(path, {"tables": {}, "next_index": 0,
                             "group_elements": {}})
        data = bytearray(open(path, "rb").read())
        data[-1] ^= 0xFF
        open(path, "wb").write(bytes(data))
        assert load_snapshot(path) is None

    def test_missing_snapshot_none(self, tmp_path):
        assert load_snapshot(str(tmp_path / "nope.snap")) is None

    def test_mutation_epoch(self):
        dm = InMemoryDeviceManagement()
        e0 = dm.mutations
        dt = dm.create_device_type(DeviceType(token="t"))
        assert dm.mutations > e0
        e1 = dm.mutations
        dm.create_device(Device(token="d", device_type_id=dt.id))
        assert dm.mutations > e1


def test_engine_restore_respects_device_status(tmp_path, run):
    """A device deactivated before the crash must not resurrect as
    registered after restore (the mask is rebuilt from entity status)."""
    from sitewhere_tpu.config import InstanceSettings, TenantConfig
    from sitewhere_tpu.kernel.service import ServiceRuntime
    from sitewhere_tpu.services import DeviceManagementService

    async def life(data_dir, first):
        rt = ServiceRuntime(InstanceSettings(instance_id="t",
                                             data_dir=data_dir))
        rt.add_service(DeviceManagementService(rt))
        await rt.start()
        await rt.add_tenant(TenantConfig(tenant_id="acme", sections={}))
        dm = rt.api("device-management").management("acme")
        if first:
            devs = dm.bootstrap_fleet(DeviceType(token="thermo"), 4)
            dm.set_device_status(devs[2].id, "inactive")
            mask = dm.registered_mask(np.arange(4))
            assert list(mask) == [True, True, False, True]
        else:
            mask = dm.registered_mask(np.arange(4))
            assert list(mask) == [True, True, False, True], list(mask)
        await rt.stop()

    data = str(tmp_path / "data")
    run(life(data, True))
    run(life(data, False))


def test_instance_users_tenants_assets_survive_restart(tmp_path, run):
    """Instance-scoped durability: users (hashed credentials), tenants
    (entities + runtime TenantConfig), and per-tenant assets all come
    back after a restart — restored tenants RESPIN their engines with
    the persisted config, and a restored user can still authenticate."""
    from sitewhere_tpu.config import InstanceSettings, TenantConfig
    from sitewhere_tpu.domain.model import Asset, AssetType, User
    from sitewhere_tpu.kernel.service import ServiceRuntime
    from sitewhere_tpu.services import (
        AssetManagementService,
        DeviceManagementService,
        InstanceManagementService,
    )

    data = str(tmp_path / "data")

    def build():
        rt = ServiceRuntime(InstanceSettings(instance_id="t",
                                             data_dir=data))
        rt.add_service(InstanceManagementService(rt, serve_rest=False))
        rt.add_service(DeviceManagementService(rt))
        rt.add_service(AssetManagementService(rt))
        return rt

    async def life1():
        rt = build()
        await rt.start()
        ims = rt.services["instance-management"]
        ims.users.create_user(User(username="ops",
                                   authorities=("REST",)), "pw123")
        await ims.create_tenant("acme", name="Acme",
                                sections={"device-management":
                                          {"snapshot_interval_s": 0.1}})
        am = rt.api("asset-management").management("acme")
        at = am.create_asset_type(AssetType(token="pump", name="Pump"))
        am.create_asset(Asset(token="p1", asset_type_id=at.id))
        await rt.stop()

    async def life2():
        rt = build()
        await rt.start()
        ims = rt.services["instance-management"]
        # restored user authenticates with the persisted salted hash
        assert ims.users.authenticate("ops", "pw123") is not None
        assert ims.users.authenticate("ops", "wrong") is None
        # admin bootstrap did not clobber restored users
        assert ims.users.authenticate("admin", "password") is not None
        # restored tenant respins (engines come up with stored config);
        # gate on the ENGINE, not the config dict — add_tenant registers
        # the config before engines finish booting
        await asyncio.wait_for(
            rt.wait_for_engine("asset-management", "acme"), 30)
        assert "acme" in rt.tenants
        cfg = rt.tenants["acme"]
        assert cfg.sections["device-management"][
            "snapshot_interval_s"] == 0.1
        assert ims.tenant_store.get_tenant_by_token("acme").name == "Acme"
        # per-tenant assets restored
        am = rt.api("asset-management").management("acme")
        assert am.get_asset_type_by_token("pump") is not None
        assert len(am.list_assets()) == 1
        await rt.stop()

    run(life1())
    run(life2())


def test_restore_snapshot_idempotent():
    """restart() re-runs restore into live state; derived maps must not
    duplicate (active assignments doubled was the failure mode)."""
    dm = InMemoryDeviceManagement()
    dt = dm.create_device_type(DeviceType(token="t"))
    d = dm.create_device(Device(token="d0", device_type_id=dt.id))
    dm.create_device_assignment(DeviceAssignment(device_id=d.id,
                                                 token="a0"))
    snap = dm.to_snapshot()
    dm.restore_snapshot(snap)
    dm.restore_snapshot(snap)
    assert len(dm.get_active_assignments_for_device(d.id)) == 1


# ---------------------------------------------------------------------------
# Chaos: kill -9 mid-stream, restart, recover
# ---------------------------------------------------------------------------

CHAOS_CHILD = r"""
import asyncio, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

import numpy as np
from sitewhere_tpu.config import InstanceSettings, TenantConfig
from sitewhere_tpu.domain.model import DeviceType
from sitewhere_tpu.kernel.service import ServiceRuntime
from sitewhere_tpu.services import (
    DeviceManagementService, EventSourcesService, InboundProcessingService,
    EventManagementService, DeviceStateService, RuleProcessingService)
from sitewhere_tpu.sim.simulator import DeviceSimulator, SimConfig

MODE = sys.argv[1]
DATA = sys.argv[2]

async def main():
    rt = ServiceRuntime(InstanceSettings(instance_id="chaos",
                                         data_dir=DATA,
                                         engine_ready_timeout_s=60))
    for cls in (DeviceManagementService, EventSourcesService,
                InboundProcessingService, EventManagementService,
                DeviceStateService, RuleProcessingService):
        rt.add_service(cls(rt))
    await rt.start()
    await rt.add_tenant(TenantConfig(tenant_id="acme", sections={{
        "event-management": {{"history": 64}},
        "rule-processing": {{"model": "zscore",
                           "model_config": {{"window": 8}},
                           "threshold": 4.0, "batch_window_ms": 1.0,
                           "buckets": [64], "capacity": 64}},
    }}))
    dm = rt.api("device-management").management("acme")
    em = rt.api("event-management").management("acme")
    eng = rt.api("rule-processing").engine("acme")

    if MODE == "first":
        dm.bootstrap_fleet(DeviceType(token="thermo", name="T"), 64)
        sim = DeviceSimulator(SimConfig(num_devices=64), tenant_id="acme")
        for k in range(20):
            batch, _ = sim.tick(t=1000.0 + k)
            em.add_measurements(batch)
        # wait for the registry snapshotter's debounce + spill fsync
        await asyncio.sleep(1.6)
        print("READY-TO-KILL", flush=True)
        await asyncio.sleep(60)   # parent SIGKILLs us here
    else:
        # second life: everything must be back before any new ingest
        assert dm.device_count() == 64, dm.device_count()
        assert em.telemetry.total_events == 20 * 64, em.telemetry.total_events
        w, valid = em.telemetry.window(np.arange(64), 8)
        assert valid.all()
        # scoring session warms from the REPLAYED store
        while not eng.session.ready:
            await asyncio.sleep(0.05)
        eng.session.reload_history()
        x, v = eng.session.ring.windows(np.arange(4))
        assert np.asarray(v).all(), "ring not warmed from replayed history"
        # pipeline still ingests after recovery
        sim = DeviceSimulator(SimConfig(num_devices=64), tenant_id="acme")
        batch, _ = sim.tick(t=2000.0)
        em.add_measurements(batch)
        assert em.telemetry.total_events == 21 * 64
        print("RECOVERED-OK", flush=True)
        await rt.stop()

asyncio.run(main())
"""


def test_kill9_recovery(tmp_path):
    """Hard-kill the process mid-stream; a restart recovers registrations,
    event history, and scoring warm-state from disk."""
    child_src = CHAOS_CHILD.format(repo=REPO)
    script = tmp_path / "chaos_child.py"
    script.write_text(child_src)
    data = str(tmp_path / "data")

    p = subprocess.Popen([sys.executable, str(script), "first", data],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, cwd=REPO)
    try:
        deadline = time.monotonic() + 60
        for line in p.stdout:
            if "READY-TO-KILL" in line:
                break
            if time.monotonic() > deadline:
                raise TimeoutError("first life never became ready")
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=10)
    finally:
        if p.poll() is None:
            p.kill()

    out = subprocess.run([sys.executable, str(script), "second", data],
                         capture_output=True, text=True, timeout=90,
                         cwd=REPO)
    assert "RECOVERED-OK" in out.stdout, (
        f"stdout: {out.stdout!r}\nstderr: {out.stderr[-3000:]!r}")
